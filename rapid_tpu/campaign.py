"""Monte-Carlo fault campaigns over a fleet of batched clusters.

The campaign driver samples scenario space with
``faults.sample_adversary_schedule`` (seeded weights over
crash/partition/flip-flop/contested/churn mixes), lowers every draw to a
device ``FleetMember`` (``engine.fleet.lower_schedule``), and runs
``fleet_size`` clusters per jitted dispatch — thousands of independent
clusters complete in one process with a single compile, since every
dispatch shares the batched program shape.

Aggregation goes through the existing telemetry layer: each member's
logs fold into a ``RunSummary`` (``telemetry.metrics.fleet_summaries``),
the fleet aggregate merges with the documented max-vs-total gauge
semantics (``merge_summaries`` / ``schema.GAUGE_SEMANTICS``), and
campaign distributions (ticks-to-decide percentiles, message-complexity
tails, invariant-violation rates) are nearest-rank percentiles over the
per-member summaries — bit-deterministic in the campaign seed.

Exactness: partition, flip-flop, and latency-family (delay / jitter /
slow-asym) members are dispatched in **per-receiver** mode
(``engine.receiver`` via ``fleet.lower_receiver_schedule`` /
``receiver_fleet_simulate``), so their reported event streams and
counters are *device-exact* under link faults and per-edge delay — no
host replay is load-bearing for them. Latency members route
per-receiver unconditionally (the shared wire has no per-edge arrival
ticks); crash / contested / churn members keep the shared-state fast
path, which is exact for those kinds. The quadratic per-receiver state
is budgeted up front (``fleet.check_receiver_budget``, including the
delivery-ring ``[D]`` axis): an oversized fleet raises a structured
``ReceiverBudgetError`` naming the measured per-member bytes before
any device allocation, never an OOM mid-campaign; delay schedules that
exceed the ring depth raise ``faults.DelayBudgetError`` at sampling.

Spot checks are belt-and-suspenders on top of that: a seeded subset of
members (≥1 partition, ≥1 contested / classic-fallback, and ≥1 delay
scenario when the check budget allows) is replayed host-side through
the per-slot oracle referee — ``diff.run_receiver_differential`` for
per-receiver kinds, ``diff.run_adversarial_differential`` for the
rest. The referee loop runs *before* the device dispatches: a
divergence aborts the campaign without burning device wall, and every
dispatch heartbeat carries the real running spot-failure count.
Churn-mix members are excluded from the spot-check pool (the referee
replays ``AdversarySchedule`` surfaces only; churn scheduling stays
engine-side, see ``engine.churn``). A diverging check no longer kills
the campaign outright: each failure writes a JSONL forensics artifact
and lands as a structured record in the payload, and the run aborts
only when failures exceed ``--max-spot-failures`` (default 0 keeps the
old strictness). This referee loop is the only host-side part of a
campaign.

Pipelined pooled dispatch (schema v7): members are first bucketed into
**kind-homogeneous pools** by schedule shape signature — shared members
split on (has link windows, has contested pids), per-receiver members
on (has link windows, has delay rules) — so a contested-heavy member no
longer inflates every crash-only member's fallback table and a
delay-only member compiles the window machinery out entirely. Each pool
stacks to its own maxima, gets its own AOT executable (compiled once,
cached per pool; trailing chunks are cache hits), and its padding waste
collapses to in-pool slack. The driver then runs the dispatch plan as a
**double-buffered pipeline**: executables are compiled with donated
carries and launched asynchronously (JAX dispatch returns immediately),
the fence moves to result-fold time, and while dispatch d executes on
device the host lowers/stacks/compiles dispatch d+1 — so
``host_blocked_s`` overlaps ``device_busy_s`` and the observatory's
``overlap_headroom_s`` is reclaimed instead of merely measured.
``--no-pipeline`` runs the identical plan serially (fence right after
launch); both drivers produce bit-identical payloads in every non-wall
field, which ``tests/test_campaign.py`` pins. ``--fleet-shard D``
additionally shards the fleet axis of every dispatch over ``D`` devices
(``engine.sharding.fleet_axis_mesh`` — whole members per device, no
collectives, bit-identical results).

Dispatch observatory: every stage of every dispatch — schedule
sampling, member lowering, stacking, the per-pool AOT XLA compile, the
(now async) execute with its residual fence wait, and the summary fold
— is timed into one ``dispatch_timeline`` record per dispatch, with
member-kind mix, pool identity and shape, padding waste against the
pool maxima, host-blocked fraction, and a device-memory watermark. The
top-level ``observatory`` block folds those into host-blocked vs
device-busy wall accounting plus the pipeline/pool summaries, and
``clusters_per_sec`` is the campaign throughput row
``scripts/bench_compare.py`` gates. ``--trace`` exports the same stages
as Perfetto wall-clock spans (``telemetry.trace``); ``--progress``
emits one JSONL heartbeat line per completed dispatch — now carrying
``pool_id``/``pool_shape`` and ``in_flight_dispatches`` — so long
pipelined campaigns are observable mid-run.

Protocol-variant tournaments (schema v11): ``--tournament rapid,ring``
runs every sampled member once per variant — same seeds, same fault
schedules, same identities (``protocol_variant`` never feeds the
scenario sampler) — and reports a ``campaign.tournament`` block:
per-variant decide tails, total message counts, fallback-member rates,
and per-kind win/loss (earlier first decide wins; decided beats
undecided; equal is a tie). Variant members run the shared-state path
only (the per-receiver engine is reference-protocol-only), so
tournament weight mixes must exclude the latency family, and the host
referee replays the reference protocol, so non-rapid campaigns reject
``--spot-checks``. Every campaign block records its
``protocol_variant`` so ``rapid_tpu.replay`` re-derives the variant
from the payload alone.

CLI::

    python -m rapid_tpu.campaign --clusters 1024 --n 64 --ticks 240 \
        --seed 0 --fleet-size 64 --spot-checks 8 --out campaign.json \
        --trace campaign_trace.json --progress -

    python -m rapid_tpu.campaign --clusters 256 --n 32 --ticks 160 \
        --weights crash=2,contested=1,churn=1 \
        --tournament rapid,ring --out tournament.json
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from rapid_tpu import hashing
from rapid_tpu.faults import (DEFAULT_SCENARIO_WEIGHTS, DELAY_KINDS,
                              SCENARIO_KINDS, SampledScenario,
                              ScenarioWeights, sample_adversary_schedule)
from rapid_tpu.settings import Settings
from rapid_tpu.telemetry import lineage as lineage_lib

__all__ = ["CampaignConfig", "run_campaign", "run_tournament", "main"]

#: Spot-check kinds the acceptance gate requires when the budget allows:
#: a partition (link-masked FD path), a contested split (classic-Paxos
#: fallback on both sides of the differential), and a delay member (the
#: delivery-ring latency path).
REQUIRED_SPOT_KINDS = ("partition", "contested", "delay")

#: Walls below this are timer noise on every supported platform; rates
#: derived from them (``ticks_per_sec``, ``clusters_per_sec``) are
#: reported as ``null`` instead of a garbage division.
MIN_MEASURABLE_WALL_S = 1e-3

#: Launched-but-unretired dispatches the pipelined driver keeps in
#: flight: classic double buffering — dispatch d executes on device
#: while the host samples/lowers/stacks/compiles d+1. Deeper queues buy
#: nothing (the host prep of d+1 is the only work to overlap) and
#: multiply the live working set.
PIPELINE_DEPTH = 2

#: Anomaly classes of the post-dispatch triage classifier, in report
#: order. Every member is tested against every class (membership is not
#: exclusive); counts and exemplar refs are seed-deterministic, so
#: ``scripts/bench_compare.py`` exact-gates the whole ``triage`` block.
TRIAGE_CLASSES = (
    "no_decide_by_deadline",   # never decided within cfg.ticks
    "slow_decide",             # decided past the campaign p99 tail
    "invariant_violations",    # on-device invariant monitor tripped
    "envelope_flags",          # per-receiver sticky envelope flags
    "excess_fallback",         # unexpected / repeated classic-Paxos rounds
    "spot_failures",           # host oracle referee divergence
)

#: Kinds for which classic-Paxos fallback traffic is the *expected*
#: resolution path (contested splits by construction; latency members
#: can starve the fast round into the timer path). Any other kind
#: sending classic traffic is an anomaly.
EXPECTED_FALLBACK_KINDS = ("contested",) + DELAY_KINDS

#: Classic rounds at/above which even an expected-fallback member is
#: flagged (one round is the designed resolution; repeats mean the
#: fallback itself is thrashing).
EXCESS_FALLBACK_ROUNDS = 2

#: Exemplar member refs embedded per triage class (first in campaign
#: index order — deterministic). Bounds the payload and the recorder
#: rings extracted to host at any fleet size.
MAX_TRIAGE_EXEMPLARS = 4


def _rate(numerator: float, wall_s: float) -> Optional[float]:
    """``numerator / wall_s``, or None when the wall is unmeasurable."""
    if wall_s < MIN_MEASURABLE_WALL_S:
        return None
    return numerator / wall_s


class _ProgressWriter:
    """``--progress`` JSONL heartbeat: one flushed, newline-terminated
    line per completed dispatch (and per spot check), so a ≥100k-cluster
    campaign is monitorable instead of silent for minutes. ``-`` streams
    to stderr; None disables at zero cost."""

    def __init__(self, path: Optional[str]) -> None:
        self._fh = None
        self._own = False
        if path == "-":
            self._fh = sys.stderr
        elif path:
            self._fh = open(path, "w")
            self._own = True

    def emit(self, record: Dict[str, object]) -> None:
        if self._fh is None:
            return
        from rapid_tpu.telemetry import json_artifact_line

        self._fh.write(json_artifact_line(record, sort_keys=True))
        self._fh.flush()

    def close(self) -> None:
        if self._own and self._fh is not None:
            self._fh.close()
            self._fh = None


@dataclasses.dataclass(frozen=True)
class CampaignConfig:
    """Knobs of one campaign; everything downstream is derived from these
    (same config => bit-identical aggregates and distributions)."""

    clusters: int = 64
    n: int = 64
    ticks: int = 240
    seed: int = 0
    fleet_size: int = 64
    headroom: int = 16          # dormant slots per cluster for churn joins
    weights: Optional[ScenarioWeights] = None
    spot_checks: int = 0
    settings: Optional[Settings] = None
    # Route partition/flip-flop members through the per-receiver engine
    # (device-exact under link faults); False forces every member onto
    # the shared-state fast path (pre-exactness behaviour, cheap).
    per_receiver: bool = True
    # Spot-check failures tolerated before the campaign aborts; each
    # failure writes a forensics artifact and a payload record either
    # way. 0 == any divergence is fatal (the historical contract).
    max_spot_failures: int = 0
    # Where divergence artifacts land (default: the system temp dir).
    artifact_dir: Optional[str] = None
    # Double-buffered dispatch: launch asynchronously with donated
    # carries and fence at fold time, overlapping device execution with
    # the next dispatch's host prep. False runs the identical plan
    # serially; every non-wall payload field is bit-identical either way.
    pipeline: bool = True
    # Shard the fleet axis of every dispatch over this many devices
    # (engine.sharding.fleet_axis_mesh: whole members per device, no
    # collectives). None keeps single-device dispatch.
    fleet_shard: Optional[int] = None
    # Persist pool executables to the on-disk XLA compilation cache
    # (engine.fleet.enable_compile_cache): re-running a campaign loads
    # each pool's program from disk instead of re-running LLVM. Same
    # programs bit-for-bit — only compile wall changes.
    compile_cache: bool = True
    # On-device flight recorder window W (engine.recorder): > 0 threads
    # a bounded [W, G] gauge ring + first-occurrence stamps through
    # every member's scan and embeds the rings of triage-flagged
    # exemplars in the payload. 0 (default) compiles the recorder out —
    # byte-identical member programs to a recorder-less build.
    flight_recorder: int = 0
    # Dissemination/consensus variant every member runs
    # (rapid_tpu.variants): "rapid" (default, byte-identical programs),
    # "ring" (segmented-scan ring aggregation, O(N) wire), or "hier"
    # (two-level seeded-group consensus). Never feeds the scenario
    # sampler, so a tournament's variants see identical schedules.
    protocol_variant: str = "rapid"


def _receiver_eligible(sc: SampledScenario) -> bool:
    """Per-receiver dispatch eligibility: link-fault-only members.

    Scripted proposes and churn are shared-path features (the
    per-receiver envelope is crash + link windows + delay rules, see
    ``engine.receiver``); crash-only members gain nothing from the
    quadratic state and stay on the fast path too. Latency-family
    members (``DELAY_KINDS``) are eligible — and in fact *required* to
    run per-receiver, which ``_delay_member`` enforces regardless of
    ``CampaignConfig.per_receiver``.
    """
    return (sc.kind in ("partition", "flip_flop") + DELAY_KINDS
            and not sc.wants_churn and not sc.schedule.proposes)


def _delay_member(sc: SampledScenario) -> bool:
    """True for members the shared fast path cannot represent at all:
    any schedule carrying delay rules (the shared wire has no per-edge
    arrival ticks, ``fleet.lower_schedule`` rejects them)."""
    return bool(sc.schedule.delays)


def _member_seed(cfg: CampaignConfig, idx: int) -> int:
    """Deterministic per-member scenario seed from the campaign seed."""
    return hashing.hash64(idx, seed=cfg.seed & hashing.MASK64) & 0x7FFFFFFF


def _sample_scenario(cfg: CampaignConfig, idx: int) -> SampledScenario:
    """Draw member ``idx``'s scenario (seeded by the campaign seed).

    Latency draws are bounded by the campaign settings' delivery-ring
    depth, so every sampled schedule lowers without a budget error."""
    ring = (cfg.settings or Settings()).delivery_ring_depth
    return sample_adversary_schedule(cfg.n, _member_seed(cfg, idx),
                                     cfg.ticks,
                                     cfg.weights or DEFAULT_SCENARIO_WEIGHTS,
                                     ring_depth=ring)


def _lower_shared(cfg: CampaignConfig, settings: Settings, idx: int,
                  sc: SampledScenario):
    """Lower one shared-state member (the pre-existing fast path)."""
    from rapid_tpu.engine import churn as churn_mod
    from rapid_tpu.engine.fleet import lower_schedule

    seed = _member_seed(cfg, idx)
    churn = id_fps = None
    if sc.wants_churn and cfg.headroom >= 2:
        rng = random.Random(seed ^ 0xC4B0)
        burst = min(cfg.headroom, rng.choice((2, 4, 8)))
        churn, id_fps, _ = churn_mod.synthetic_churn_schedule(
            cfg.n + cfg.headroom, cfg.n, settings,
            start=rng.randint(5, 25), burst=burst)
    return lower_schedule(sc.schedule, settings, churn=churn,
                          id_fps=id_fps)


def _chunks(seq: List[int], size: int) -> List[List[int]]:
    return [seq[i:i + size] for i in range(0, len(seq), size)]


# --- kind-homogeneous dispatch pools --------------------------------------
#
# Stacking pads every member to the dispatch maxima, so one
# contested-heavy member used to inflate every crash-only member's
# fallback table (90+ inert pid rows per shared dispatch in the v6
# baseline) and one partition member taxed every delay member with dead
# window planes. Pools bucket members by *shape signature* — which
# padded dimensions are live at all — before stacking: within a pool
# the maxima are tight, and a small-signature pool's executable
# compiles the dead machinery out entirely. Signatures derive from the
# sampled schedule alone (no lowering needed), so the dispatch plan is
# known up front and is bit-deterministic in the campaign seed.

def _shared_dims(sc: SampledScenario) -> Tuple[int, int, int]:
    """(window_rows, fallback_instances, fallback_pids) a shared member
    lowers to — mirrors ``fleet.lower_schedule``/``_compile_proposes``
    exactly (``stack_members`` re-derives and cross-checks them)."""
    values = {tuple(p.proposal) for p in sc.schedule.proposes}
    return (len(sc.schedule.windows), 1, max(1, len(values)))


def _rx_dims(sc: SampledScenario) -> Tuple[int, int]:
    """(window_rows, delay_rules) a per-receiver member lowers to."""
    return (len(sc.schedule.windows), len(sc.schedule.delays))


def _shared_pool_key(dims: Tuple[int, int, int]) -> Tuple[bool, bool]:
    """Shared shape signature: (has link windows, has contested pids)."""
    return (dims[0] > 0, dims[2] > 1)


def _rx_pool_key(dims: Tuple[int, int]) -> Tuple[bool, bool]:
    """Per-receiver shape signature: (has link windows, has delays)."""
    return (dims[0] > 0, dims[1] > 0)


def _pool_shape_dict(mode: str, shape: Tuple[int, ...]) -> Dict[str, int]:
    """A pool's stacking maxima in the padding-record key space."""
    if mode == "shared":
        return {"window_rows": shape[0], "fallback_instances": shape[1],
                "fallback_pids": shape[2], "delay_rules": 0}
    return {"window_rows": shape[0], "fallback_instances": 0,
            "fallback_pids": 0, "delay_rules": shape[1]}


def _build_pools(scenarios: List[SampledScenario], sh_idx: List[int],
                 rx_idx: List[int], f: int) -> List[Dict[str, object]]:
    """Group members into (mode, shape-signature) pools.

    Pools are ordered shared-first then per-receiver, each by sorted
    signature; members keep campaign index order within a pool — all
    deterministic in the sampled scenarios, so serial and pipelined
    drivers (and repeated runs) share one dispatch plan. Each pool's
    fleet size is capped at its own membership so a three-member pool
    compiles a three-member executable, not a padded campaign-wide one.
    """
    pools: List[Dict[str, object]] = []

    def add(mode, idxs, dims_fn, key_fn):
        dims_map = {i: dims_fn(scenarios[i]) for i in idxs}
        groups: Dict[Tuple[bool, ...], List[int]] = {}
        for i in idxs:
            groups.setdefault(key_fn(dims_map[i]), []).append(i)
        for key in sorted(groups):
            members = groups[key]
            ndim = len(dims_map[members[0]])
            shape = tuple(max(dims_map[i][j] for i in members)
                          for j in range(ndim))
            pools.append({
                "pool_id": len(pools), "mode": mode, "members": members,
                "dims": {i: dims_map[i] for i in members},
                "shape": shape, "fleet_size": min(f, len(members)),
            })

    add("shared", sh_idx, _shared_dims, _shared_pool_key)
    add("per_receiver", rx_idx, _rx_dims, _rx_pool_key)
    return pools


def _spot_check(cfg: CampaignConfig, scenarios: List[SampledScenario],
                referee_settings: Settings, writer=None,
                progress: Optional[_ProgressWriter] = None
                ) -> Dict[str, object]:
    """Replay a seeded member subset through the host oracle referee.

    Per-receiver-eligible kinds replay through
    ``run_receiver_differential`` (the same engine that ran the member
    on device — belt-and-suspenders on the device-exact claim); the
    rest through ``run_adversarial_differential``. Members whose
    scenario wants churn are ineligible (the referee replays fault
    surfaces only); if a required kind is missing from the eligible
    pool, a fresh forced scenario of that kind is synthesized from the
    campaign seed and checked as member ``-1``.

    A divergence no longer dies in place: the failing check writes a
    JSONL forensics artifact, lands as a structured member record
    (``passed=False`` + error + artifact path), and the campaign aborts
    only once failures exceed ``cfg.max_spot_failures`` — whose default
    of 0 preserves the historical any-divergence-is-fatal contract.
    """
    from rapid_tpu.engine.diff import (run_adversarial_differential,
                                       run_receiver_differential)
    from rapid_tpu.engine.receiver import ReceiverEnvelopeError
    from rapid_tpu.telemetry.forensics import DivergenceError
    from rapid_tpu.telemetry.trace import wall_span

    requested = cfg.spot_checks
    block: Dict[str, object] = {"requested": requested, "run": 0,
                                "passed": 0, "failed": 0,
                                "max_failures": cfg.max_spot_failures,
                                "members": []}
    if requested <= 0:
        return block
    rng = random.Random(cfg.seed ^ 0x5EED)
    eligible = [i for i, sc in enumerate(scenarios) if not sc.wants_churn]
    chosen: List[Tuple[int, SampledScenario]] = []
    used = set()
    for kind in REQUIRED_SPOT_KINDS[:requested]:
        pool = [i for i in eligible
                if scenarios[i].kind == kind and i not in used]
        if pool:
            i = rng.choice(pool)
            used.add(i)
            chosen.append((i, scenarios[i]))
        else:  # tiny campaign without this kind: force one
            forced_seed = hashing.hash64(
                len(chosen), seed=(cfg.seed ^ 0xF0CE) & hashing.MASK64
            ) & 0x7FFFFFFF
            weights = ScenarioWeights(
                **{k: (1.0 if k == kind else 0.0)
                   for k in SCENARIO_KINDS})
            forced = sample_adversary_schedule(
                cfg.n, forced_seed, cfg.ticks, weights,
                ring_depth=referee_settings.delivery_ring_depth)
            chosen.append((-1, forced))
    rest = [i for i in eligible if i not in used]
    rng.shuffle(rest)
    for i in rest[:max(0, requested - len(chosen))]:
        chosen.append((i, scenarios[i]))

    art_dir = cfg.artifact_dir or tempfile.gettempdir()
    for idx, sc in chosen:
        per_rx = ((cfg.per_receiver and _receiver_eligible(sc))
                  or _delay_member(sc))
        runner = run_receiver_differential if per_rx \
            else run_adversarial_differential
        artifact = os.path.join(
            art_dir, f"rapid_tpu_spot_m{idx}_{sc.kind}_"
                     f"{sc.schedule.seed}.jsonl")
        record: Dict[str, object] = {
            "member": idx, "kind": sc.kind, "seed": sc.schedule.seed,
            "mode": "per_receiver" if per_rx else "shared",
            "passed": True, "artifact": None, "error": None}
        block["run"] += 1
        try:
            with wall_span(writer, "spot_check",
                           {"member": idx, "kind": sc.kind,
                            "mode": record["mode"]}):
                result = runner(sc.schedule, cfg.ticks, referee_settings)
                result.assert_identical(artifact=artifact)
            block["passed"] += 1
        except (DivergenceError, ReceiverEnvelopeError) as err:
            record["passed"] = False
            record["artifact"] = artifact if os.path.exists(artifact) \
                else None
            record["error"] = str(err).splitlines()[0]
            block["failed"] += 1
        block["members"].append(record)
        if progress is not None:
            progress.emit({"record": "spot_check", "member": idx,
                           "kind": sc.kind, "passed": record["passed"],
                           "run": block["run"],
                           "requested": block["requested"],
                           "spot_failures": block["failed"]})
    if block["failed"] > cfg.max_spot_failures:
        bad = [m for m in block["members"] if not m["passed"]]
        raise RuntimeError(
            f"{block['failed']} spot-check divergence(s) exceed "
            f"--max-spot-failures={cfg.max_spot_failures}: "
            + "; ".join(
                f"member {m['member']} ({m['kind']}, seed {m['seed']}): "
                f"{m['error']}" + (f" [forensics: {m['artifact']}]"
                                   if m["artifact"] else "")
                for m in bad))
    return block


def _expected_block(s, meta: Dict[str, object]) -> Dict[str, object]:
    """The bit-identity contract one member's replay must reproduce
    (``rapid_tpu.replay`` re-runs the member unbatched and diffs every
    field here against the fresh fold)."""
    return {
        "ticks_to_first_announce": s.ticks_to_first_announce,
        "ticks_to_first_decide": s.ticks_to_first_decide,
        "announcements": s.announcements,
        "decisions": s.decisions,
        "invariant_violations": s.invariant_violations,
        "counter_totals": {
            "sent": s.total_sent, "delivered": s.total_delivered,
            "dropped": s.total_dropped, "timeouts": s.total_timeouts,
            "probes_sent": s.total_probes_sent,
            "probes_failed": s.total_probes_failed},
        "fallback_phase_sent": dict(s.fallback_phase_sent),
        "config_ids": list(meta["config_ids"]),
        "flags": meta["flags"],
    }


def _classic_rounds(s, n: int) -> int:
    """Estimated classic-Paxos rounds from phase-1a traffic (one round
    is one coordinator broadcast to ~n acceptors; the factor fold makes
    the totals exact, so the estimate is deterministic)."""
    p1a = int(s.fallback_phase_sent.get("phase1a", 0))
    return -(-p1a // max(1, n - 1)) if p1a else 0


def _triage(cfg: CampaignConfig, scenarios, summaries, member_order,
            member_meta, dists, spot) -> Dict[str, object]:
    """Classify every member into ``TRIAGE_CLASSES``; returns the
    schema-v8 ``campaign.triage`` block (recorder rings are attached to
    exemplars by the caller, which owns the per-dispatch host copies).

    Every field is derived from seed-deterministic folds — no
    wall-clock values — so ``bench_compare``'s exact campaign-block
    gate covers the whole block.
    """
    tail = dists.get("ticks_to_first_decide") or {}
    slow_thr = tail.get("p99")
    per_member_classes: Dict[int, List[str]] = {}

    def hits(s, meta, kind) -> List[str]:
        out = []
        if s.ticks_to_first_decide is None:
            out.append("no_decide_by_deadline")
        elif slow_thr is not None and s.ticks_to_first_decide > slow_thr:
            out.append("slow_decide")
        if s.invariant_violations:
            out.append("invariant_violations")
        if meta["flags"]:
            out.append("envelope_flags")
        classic = sum(int(s.fallback_phase_sent.get(p, 0))
                      for p in ("phase1a", "phase1b", "phase2a", "phase2b"))
        if classic and (kind not in EXPECTED_FALLBACK_KINDS
                        or _classic_rounds(s, cfg.n)
                        >= EXCESS_FALLBACK_ROUNDS):
            out.append("excess_fallback")
        return out

    classes: Dict[str, Dict[str, object]] = {
        name: {"count": 0, "by_kind": {}, "exemplars": []}
        for name in TRIAGE_CLASSES}
    for pos, i in enumerate(member_order):
        s, meta = summaries[pos], member_meta[pos]
        kind = scenarios[i].kind
        names = hits(s, meta, kind)
        if names:
            per_member_classes[i] = names
        for name in names:
            block = classes[name]
            block["count"] += 1
            block["by_kind"][kind] = block["by_kind"].get(kind, 0) + 1
            if len(block["exemplars"]) < MAX_TRIAGE_EXEMPLARS:
                block["exemplars"].append({
                    "dispatch": meta["dispatch"],
                    "member_index": meta["member_index"],
                    "member": i, "kind": kind, "mode": meta["mode"],
                    "seed": _member_seed(cfg, i),
                    "expected": _expected_block(s, meta),
                    "recorder": None,
                })

    ref_by_member = {i: (member_meta[pos]["dispatch"],
                         member_meta[pos]["member_index"],
                         member_meta[pos]["mode"])
                     for pos, i in enumerate(member_order)}
    sf = classes["spot_failures"]
    for rec in spot.get("members", ()):
        if rec["passed"]:
            continue
        i = rec["member"]
        d, j, mode = ref_by_member.get(i, (-1, -1, rec["mode"]))
        sf["count"] += 1
        sf["by_kind"][rec["kind"]] = sf["by_kind"].get(rec["kind"], 0) + 1
        if i >= 0:
            per_member_classes.setdefault(i, []).append("spot_failures")
        if len(sf["exemplars"]) < MAX_TRIAGE_EXEMPLARS:
            sf["exemplars"].append({
                "dispatch": d, "member_index": j, "member": i,
                "kind": rec["kind"], "mode": mode, "seed": rec["seed"],
                "expected": None, "recorder": None,
            })

    for block in classes.values():
        block["by_kind"] = dict(sorted(block["by_kind"].items()))
    recorder_cfg = None
    if cfg.flight_recorder:
        from rapid_tpu.engine import recorder as recorder_lib

        recorder_cfg = {"window": cfg.flight_recorder,
                        "gauges": list(recorder_lib.GAUGE_NAMES)}
    return {
        "clusters": len(member_order),
        "flagged_members": len(per_member_classes),
        "thresholds": {"slow_decide_p99": slow_thr,
                       "excess_fallback_rounds": EXCESS_FALLBACK_ROUNDS},
        "recorder": recorder_cfg,
        "classes": classes,
    }


def _live_buffer_bytes(jax) -> int:
    """Process-wide live device-buffer watermark (bytes)."""
    try:
        return int(sum(getattr(a, "nbytes", 0) or 0
                       for a in jax.live_arrays()))
    except Exception:
        return 0


def _device_peak_bytes(jax) -> Optional[int]:
    """Allocator peak from ``device.memory_stats()``; None on backends
    that expose no stats (CPU)."""
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak is not None else None


def run_campaign(cfg: CampaignConfig, *, trace_path: Optional[str] = None,
                 progress_path: Optional[str] = None,
                 member_stats_out: Optional[List[Dict[str, object]]] = None,
                 ) -> Dict[str, object]:
    """Run one campaign; returns a schema-v7 bench run payload.

    The payload validates as an ``engine_tick`` run (``telemetry`` is the
    fleet-merged ``RunSummary``) and additionally carries the
    ``campaign`` block (scenario-kind counts, dispatch pools, spot-check
    results, nearest-rank distributions, per-delay-regime
    ticks-to-first-decide tails) plus the dispatch observatory:
    ``dispatch_timeline`` (one per-stage wall record per dispatch, with
    its pool identity), ``observatory`` (host-blocked vs device-busy vs
    compile wall accounting plus the pipeline block), and
    ``clusters_per_sec``. ``wall_s`` is the end-to-end campaign wall —
    sampling, lowering, stacking, the per-pool AOT compiles, execution,
    and folds; the per-dispatch stage walls sum to it within
    ``schema.STAGE_SUM_TOLERANCE`` (under the pipeline, ``execute`` is
    the *residual* fence wait — device time hidden behind host prep
    appears in no stage, which is the point). Oracle spot-check replay
    runs first (fail-fast, before any device dispatch) and is outside
    ``wall_s`` (``spot_check_s``; ``total_s`` is the sum).

    ``trace_path`` exports the stages as Perfetto wall-clock spans;
    ``progress_path`` streams a JSONL heartbeat (``-`` for stderr).
    Both are I/O knobs, not campaign identity — everything derived from
    ``cfg`` stays bit-identical with or without them, and
    ``cfg.pipeline`` / ``cfg.fleet_shard`` change wall-clock fields
    only.
    """
    import jax
    import numpy as np

    from rapid_tpu.engine import receiver as receiver_mod
    from rapid_tpu.engine import recorder as recorder_mod
    from rapid_tpu.engine import sharding as sharding_mod
    from rapid_tpu.engine.fleet import (check_receiver_budget,
                                        fleet_aot_compile,
                                        lower_receiver_schedule,
                                        receiver_fleet_aot_compile,
                                        stack_members,
                                        stack_receiver_members)
    from rapid_tpu.telemetry.metrics import (fleet_summaries,
                                             merge_summaries,
                                             regime_distributions,
                                             summarize,
                                             summary_distributions)
    from rapid_tpu.telemetry.schema import SCHEMA_VERSION
    from rapid_tpu.telemetry.trace import TraceWriter, wall_span

    from rapid_tpu.variants import VARIANTS

    if cfg.protocol_variant not in VARIANTS:
        raise ValueError(f"protocol_variant must be one of {VARIANTS}, "
                         f"got {cfg.protocol_variant!r}")
    non_rapid = cfg.protocol_variant != "rapid"
    if non_rapid:
        w = cfg.weights or DEFAULT_SCENARIO_WEIGHTS
        hot = [k for k in DELAY_KINDS if getattr(w, k) > 0]
        if hot:
            raise ValueError(
                f"protocol_variant={cfg.protocol_variant!r} cannot run "
                f"latency-family members {hot}: delay schedules dispatch "
                f"through the per-receiver engine, which runs the "
                f"reference protocol only — zero the {DELAY_KINDS} "
                f"weights for variant campaigns")
        if cfg.spot_checks:
            raise ValueError(
                f"protocol_variant={cfg.protocol_variant!r} rejects "
                f"spot_checks={cfg.spot_checks}: the host referee replays "
                f"the reference protocol (use "
                f"engine.diff.run_variant_differential for variant "
                f"exactness)")

    base = cfg.settings or Settings()
    if base.protocol_variant != cfg.protocol_variant:
        base = base.with_(protocol_variant=cfg.protocol_variant)
    c = cfg.n + cfg.headroom
    settings = base if base.capacity == c else base.with_(capacity=c)
    referee_settings = base if base.capacity == 0 else base.with_(capacity=0)
    # Per-receiver members never churn, so they boot without the churn
    # headroom — the quadratic state is sized to N, not N + headroom.
    rx_settings = base if base.capacity == cfg.n \
        else base.with_(capacity=cfg.n)
    # The recorder rides the member settings only: the referee replays
    # host-side and must keep tracing the recorder-less programs.
    if cfg.flight_recorder:
        settings = settings.with_(flight_recorder_window=cfg.flight_recorder)
        rx_settings = rx_settings.with_(
            flight_recorder_window=cfg.flight_recorder)
    f = max(1, cfg.fleet_size)
    # Sampled membership rounds up to whole fleets of f (the historical
    # contract); the pooled plan below may split those members into more
    # (smaller) dispatches than total/f.
    total = -(-cfg.clusters // f) * f
    fleet_mesh = (sharding_mod.fleet_axis_mesh(cfg.fleet_shard)
                  if cfg.fleet_shard else None)
    if cfg.compile_cache:
        from rapid_tpu.engine.fleet import enable_compile_cache
        enable_compile_cache()

    writer = TraceWriter() if trace_path else None
    progress = _ProgressWriter(progress_path)
    t_begin = time.perf_counter()

    # Stage walls are measured per member here and attributed to each
    # member's dispatch below, so the timeline shows what every dispatch
    # *cost*, while the trace shows when the work actually ran.
    sample_s: Dict[int, float] = {}
    scenarios: List[SampledScenario] = []
    with wall_span(writer, "sample", {"clusters": total}):
        for i in range(total):
            t0 = time.perf_counter()
            scenarios.append(_sample_scenario(cfg, i))
            sample_s[i] = time.perf_counter() - t0
    # Non-rapid variants live in the shared-state engine only: route
    # everything shared (latency members — the one kind the shared wire
    # cannot carry — were rejected above before sampling).
    per_rx = cfg.per_receiver and not non_rapid
    rx_idx = [i for i, sc in enumerate(scenarios)
              if (per_rx and _receiver_eligible(sc))
              or _delay_member(sc)]
    sh_idx = [i for i in range(total) if i not in set(rx_idx)]

    # Spot checks run *before* any device dispatch: a divergence aborts
    # the campaign without burning device wall, and every dispatch
    # heartbeat below can carry the real failure count instead of a
    # placeholder. ``spot_s`` is excluded from ``wall_s`` (the referee
    # replay is host-side work outside the campaign pipeline).
    t0 = time.perf_counter()
    spot = _spot_check(cfg, scenarios, referee_settings, writer=writer,
                       progress=progress)
    spot_s = time.perf_counter() - t0
    # Budget refusal first: an oversized per-receiver fleet raises the
    # structured ReceiverBudgetError before any member is lowered.
    fr = min(f, len(rx_idx)) if rx_idx else 0
    if rx_idx:
        check_receiver_budget(max(rx_settings.capacity, cfg.n), fr,
                              rx_settings)
    # The dispatch plan: kind-homogeneous pools (shape signatures from
    # the sampled schedules — no lowering needed), chunked to each
    # pool's fleet size. Deterministic in the campaign seed, shared by
    # the serial and pipelined drivers.
    pools = _build_pools(scenarios, sh_idx, rx_idx, f)
    plan = [(pool, chunk) for pool in pools
            for chunk in _chunks(pool["members"], pool["fleet_size"])]

    lower_s: Dict[int, float] = {}
    sh_members: Dict[int, object] = {}
    rx_members: Dict[int, object] = {}
    timeline: List[Dict[str, object]] = []
    pool_compiles: List[Dict[str, object]] = []
    executables: Dict[int, object] = {}
    summaries = []
    member_order: List[int] = []  # member index per summaries[] entry
    # Per-member triage inputs, aligned with summaries/member_order:
    # mode, (dispatch, member_index) ref, sticky flags word, final
    # config ids. Plus the host copy of each dispatch's recorder rings
    # (the compact [F, W, G] carry — bounded by design; the full
    # [F, T, ...] logs never leave the fold).
    member_meta: List[Dict[str, object]] = []
    dispatch_recs: Dict[int, object] = {}
    # Per-member lineage span lists, aligned with summaries/member_order
    # (schema v12): folded at retire time from the same logs the
    # summaries come from, so the campaign never re-runs anything.
    lineage_members: List[List[Dict[str, object]]] = []
    anomalies = {"no_decide_by_deadline": 0, "invariant_violations": 0,
                 "envelope_flags": 0}
    rx_dispatches = 0
    done = 0
    in_flight: List[Dict[str, object]] = []  # FIFO, launch order
    depth = PIPELINE_DEPTH if cfg.pipeline else 1
    peak_in_flight = 0
    launched = 0

    def _launch(pool, chunk):
        """Lower/stack/compile this dispatch and launch it async.

        With donated carries the executable call returns immediately
        (JAX async dispatch); the fence lives in ``_retire``. The input
        fleet reference is dropped here — donation consumes its buffers.
        """
        nonlocal launched, peak_in_flight
        mode, pid = pool["mode"], pool["pool_id"]
        fsize, shape, dims = pool["fleet_size"], pool["shape"], pool["dims"]
        d = launched
        launched += 1
        # Trailing partial chunks pad by cycling their own members so
        # every dispatch of a pool keeps that pool's program shape;
        # padded summaries are dropped at fold.
        padded = chunk + [chunk[i % len(chunk)]
                          for i in range(fsize - len(chunk))]
        with wall_span(writer, "lower",
                       {"dispatch": d, "mode": mode, "pool": pid,
                        "members": len(chunk)}):
            for i in chunk:
                t0 = time.perf_counter()
                if mode == "shared":
                    sh_members[i] = _lower_shared(cfg, settings, i,
                                                  scenarios[i])
                else:
                    rx_members[i] = lower_receiver_schedule(
                        scenarios[i].schedule, rx_settings,
                        fleet_size=fsize)
                lower_s[i] = time.perf_counter() - t0
        t0 = time.perf_counter()
        with wall_span(writer, "stack",
                       {"dispatch": d, "mode": mode, "pool": pid}):
            if mode == "shared":
                fleet = stack_members([sh_members[i] for i in padded],
                                      n_windows=shape[0],
                                      n_instances=shape[1],
                                      n_pids=shape[2])
            else:
                fleet = stack_receiver_members(
                    [rx_members[i] for i in padded],
                    n_windows=shape[0], n_delay_rules=shape[1])
            if fleet_mesh is not None:
                fleet = sharding_mod.fleet_axis_put(fleet, fleet_mesh,
                                                    fsize)
        stack_s = time.perf_counter() - t0
        # The lowered members are only inputs to the stack: drop them so
        # a long campaign's live set is the in-flight dispatches, not
        # every member ever lowered.
        for i in chunk:
            (sh_members if mode == "shared" else rx_members).pop(i)
        compile_s = 0.0
        compiled_now = pid not in executables
        if compiled_now:
            t0 = time.perf_counter()
            with wall_span(writer, "compile",
                           {"dispatch": d, "mode": mode, "pool": pid}):
                if mode == "shared":
                    exe, info = fleet_aot_compile(
                        fleet, cfg.ticks, settings,
                        fleet_mesh=fleet_mesh, donate=True)
                else:
                    exe, info = receiver_fleet_aot_compile(
                        fleet, cfg.ticks, rx_settings,
                        fleet_mesh=fleet_mesh, donate=True)
                executables[pid] = exe
                pool_compiles.append({"pool_id": pid, "mode": mode,
                                      **info})
            compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        if mode == "shared":
            result = executables[pid](fleet.state, fleet.faults,
                                      fleet.churn, fleet.fallback)
        else:
            result = executables[pid](fleet.state, fleet.faults)
        launch_s = time.perf_counter() - t0
        pad_dims = [dims[i] for i in padded]
        if mode == "shared":
            padding = {
                "window_rows": fsize * shape[0] - sum(
                    dd[0] for dd in pad_dims),
                "fallback_instances": fsize * shape[1] - sum(
                    dd[1] for dd in pad_dims),
                "fallback_pids": fsize * shape[2] - sum(
                    dd[2] for dd in pad_dims),
                "delay_rules": 0}
        else:
            padding = {
                "window_rows": fsize * shape[0] - sum(
                    dd[0] for dd in pad_dims),
                "fallback_instances": 0, "fallback_pids": 0,
                "delay_rules": fsize * shape[1] - sum(
                    dd[1] for dd in pad_dims)}
        # The fleet reference rides along until the fence: deleting the
        # not-donatable input buffers while the computation is in flight
        # blocks the host until it finishes (a hidden fence that would
        # serialize the pipeline and hide device time from every stage).
        in_flight.append({
            "index": d, "pool": pool, "chunk": chunk, "result": result,
            "fleet": fleet,
            "compiled_now": compiled_now, "padding": padding,
            "stages": {"sample": sum(sample_s[i] for i in chunk),
                       "lower": sum(lower_s[i] for i in chunk),
                       "stack": stack_s, "compile": compile_s},
            "launch_s": launch_s})
        peak_in_flight = max(peak_in_flight, len(in_flight))

    def _retire(entry):
        """Fence the oldest in-flight dispatch, fold it, and record it.

        Retirement order is launch order (FIFO), so the summaries /
        member order / timeline are identical to the serial driver's —
        only the wall-clock fields differ.
        """
        nonlocal rx_dispatches, done
        pool, chunk = entry["pool"], entry["chunk"]
        mode, pid, d = pool["mode"], pool["pool_id"], entry["index"]
        t0 = time.perf_counter()
        with wall_span(writer, "execute",
                       {"dispatch": d, "mode": mode, "pool": pid,
                        "fleet_size": pool["fleet_size"]}):
            jax.block_until_ready(entry["result"])
        wait_s = time.perf_counter() - t0
        # Computation done: dropping the input reference is now free, and
        # the donated buffers it pinned are released before the fold.
        entry.pop("fleet")
        if cfg.flight_recorder:
            finals, logs, recs = entry["result"]
            # Host copy of the compact recorder carry; triage slices
            # out only the flagged members' rings at the end.
            dispatch_recs[d] = jax.tree_util.tree_map(np.asarray, recs)
        else:
            finals, logs = entry["result"]
        t0 = time.perf_counter()
        with wall_span(writer, "fold",
                       {"dispatch": d, "mode": mode, "pool": pid}):
            if mode == "shared":
                summaries.extend(fleet_summaries(logs)[:len(chunk)])
                cfg_hi = np.asarray(logs.config_hi)[:len(chunk), -1]
                cfg_lo = np.asarray(logs.config_lo)[:len(chunk), -1]
                fleet_cols = lineage_lib.engine_phase_columns(logs)
                for j in range(len(chunk)):
                    cid = int(cfg_hi[j]) << 32 | int(cfg_lo[j])
                    member_meta.append({
                        "dispatch": d, "member_index": j,
                        "mode": mode, "flags": 0,
                        "config_ids": [f"{cid:016x}"]})
                    lineage_members.append(
                        lineage_lib.fold_spans(fleet_cols.member(j)))
            else:
                rx_dispatches += 1
                for j in range(len(chunk)):
                    # Packed fleets return packed finals (the memory
                    # diet covers dispatch outputs); the view shim
                    # unpacks just the fields the fold reads.
                    mrs = receiver_mod.receiver_final_view(
                        jax.tree_util.tree_map(lambda x, j=j: x[j],
                                               finals))
                    mlog = jax.tree_util.tree_map(lambda x, j=j: x[j],
                                                  logs)
                    # A nonzero envelope flag voids the device-exact
                    # claim for this member; it used to abort the
                    # campaign, now it lands in the triage
                    # ``envelope_flags`` class (with the flag word in
                    # the member record) so a 100k-cluster campaign
                    # reports the escape instead of dying on it.
                    flags = int(np.asarray(mrs.flags))
                    cids = sorted(set(
                        receiver_mod.receiver_config_ids(mrs)[:cfg.n]))
                    member_meta.append({
                        "dispatch": d, "member_index": j,
                        "mode": mode, "flags": flags,
                        "config_ids": [f"{cid:016x}" for cid in cids]})
                    run = receiver_mod.receiver_run_payload(
                        mrs, mlog, cfg.n, cfg.ticks)
                    summaries.append(summarize(run.metrics()))
                    spans = lineage_lib.fold_spans(
                        lineage_lib.receiver_phase_columns(mlog))
                    sched = scenarios[chunk[j]].schedule
                    if sched.delays:
                        for sp in spans:
                            sp["critical_path"] = \
                                lineage_lib.receiver_critical_path(
                                    mlog, sp, sched)
                    lineage_members.append(spans)
            member_order.extend(chunk)
            for s, meta in zip(summaries[-len(chunk):],
                               member_meta[-len(chunk):]):
                if s.ticks_to_first_decide is None:
                    anomalies["no_decide_by_deadline"] += 1
                if s.invariant_violations:
                    anomalies["invariant_violations"] += 1
                if meta["flags"]:
                    anomalies["envelope_flags"] += 1
            # The memory watermark walks every live buffer in the
            # process — real host work, so it bills to the fold stage
            # rather than hiding as unaccounted glue between stages.
            memory = {"live_buffer_bytes": _live_buffer_bytes(jax),
                      "device_peak_bytes": _device_peak_bytes(jax)}
        fold_stage_s = time.perf_counter() - t0
        done += len(chunk)
        kinds: Dict[str, int] = {}
        for i in chunk:
            kinds[scenarios[i].kind] = kinds.get(scenarios[i].kind, 0) + 1
        stages = dict(entry["stages"])
        stages["execute"] = entry["launch_s"] + wait_s
        stages["fold"] = fold_stage_s
        wall = sum(stages.values())
        rec = {
            "index": len(timeline),
            "mode": mode,
            "pool_id": pid,
            "pool_shape": _pool_shape_dict(mode, pool["shape"]),
            "members": len(chunk),
            "pad_members": pool["fleet_size"] - len(chunk),
            "fleet_size": pool["fleet_size"],
            "kinds": dict(sorted(kinds.items())),
            "compiled": entry["compiled_now"],
            "stages": {k: round(v, 6) for k, v in stages.items()},
            "wall_s": round(wall, 6),
            "clusters_per_sec": _rate(len(chunk), wall),
            "host_blocked_frac": (
                (wall - stages["execute"]) / wall
                if wall >= MIN_MEASURABLE_WALL_S else None),
            "padding": entry["padding"],
            "memory": memory,
        }
        timeline.append(rec)
        # Schema v9: heartbeat throughput — virtual ticks retired and
        # protocol events (announces + decides) observed across this
        # dispatch's members, over the dispatch wall. Same null-below-
        # the-floor convention as every other rate.
        events = sum(s.announcements + s.decisions
                     for s in summaries[-len(chunk):])
        progress.emit({"record": "dispatch", "index": rec["index"],
                       "mode": mode, "pool_id": pid,
                       "pool_shape": rec["pool_shape"],
                       "in_flight_dispatches": len(in_flight),
                       "clusters_done": done,
                       "clusters_total": total, "stages": rec["stages"],
                       "spot_failures": spot["failed"],
                       "anomalies": dict(anomalies),
                       "ticks_per_sec": _rate(len(chunk) * cfg.ticks, wall),
                       "events_per_sec": _rate(events, wall)})
        return rec

    # The driver: launch each planned dispatch, retiring the oldest
    # whenever the in-flight queue is full. depth == 1 is the serial
    # driver (fence right after launch); depth == 2 double-buffers.
    for pool, chunk in plan:
        _launch(pool, chunk)
        while len(in_flight) >= depth:
            _retire(in_flight.pop(0))
    while in_flight:
        _retire(in_flight.pop(0))

    boot_s = sum(sample_s.values()) + sum(lower_s.values())
    dispatches = len(plan)

    # Spot checks ran inside the t_begin..now window but are host referee
    # work, not campaign pipeline — subtract them so ``wall_s`` keeps its
    # meaning (sampling + lowering + stacking + compile + execute + fold).
    wall_s = time.perf_counter() - t_begin - spot_s
    compile_total = sum(r["stages"]["compile"] for r in timeline)
    device_busy_s = sum(r["stages"]["execute"] for r in timeline)
    fold_s = sum(r["stages"]["fold"] for r in timeline)
    host_blocked_s = max(0.0, wall_s - device_busy_s - compile_total)

    merged = merge_summaries(summaries)
    dists = summary_distributions(summaries)
    kinds: Dict[str, int] = {}
    for sc in scenarios:
        kinds[sc.kind] = kinds.get(sc.kind, 0) + 1

    # Tail-latency accounting per delay regime: every member belongs to
    # exactly one regime (its sampled latency kind, or "no_delay"), and
    # the block reports the nearest-rank ticks-to-first-decide tail of
    # each regime present in the campaign.
    regime_ticks: Dict[str, List[float]] = {}
    for i, s in zip(member_order, summaries):
        regime = scenarios[i].kind \
            if scenarios[i].kind in DELAY_KINDS else "no_delay"
        regime_ticks.setdefault(regime, [])
        if s.ticks_to_first_decide is not None:
            regime_ticks[regime].append(s.ticks_to_first_decide)
    delay_regimes = regime_distributions(regime_ticks)

    # Per-member tournament rows: everything ``run_tournament`` joins
    # across variants, keyed by campaign member index (sorted, so the
    # dispatch plan's pool order never leaks into the join). Derived
    # from seed-deterministic folds only.
    if member_stats_out is not None:
        rows = []
        for pos, i in enumerate(member_order):
            s = summaries[pos]
            classic = sum(int(s.fallback_phase_sent.get(p, 0))
                          for p in ("phase1a", "phase1b",
                                    "phase2a", "phase2b"))
            rows.append({
                "member": i, "kind": scenarios[i].kind,
                "seed": _member_seed(cfg, i),
                "decided": s.ticks_to_first_decide is not None,
                "decide_tick": s.ticks_to_first_decide,
                "total_sent": s.total_sent,
                "fallback": classic > 0,
                "lineage_spans": lineage_members[pos],
            })
        rows.sort(key=lambda r: r["member"])
        member_stats_out.extend(rows)

    # Post-dispatch triage: classify every member, then attach the
    # flight-recorder rings of the (bounded) exemplar set only — the
    # per-dispatch host copies hold every member's compact ring, but
    # only flagged exemplars reach the payload.
    triage = _triage(cfg, scenarios, summaries, member_order, member_meta,
                     dists, spot)
    if cfg.flight_recorder:
        for block in triage["classes"].values():
            for ex in block["exemplars"]:
                recs = dispatch_recs.get(ex["dispatch"])
                if recs is not None and ex["member_index"] >= 0:
                    ex["recorder"] = recorder_mod.recorder_payload(
                        recorder_mod.member_recorder(
                            recs, ex["member_index"]))
    # Schema v12: exemplars carry their member's lineage span list (null
    # only for forced spot-check refs that never ran in the fleet).
    lineage_by_ref = {
        (meta["dispatch"], meta["member_index"]): spans
        for meta, spans in zip(member_meta, lineage_members)}
    for block in triage["classes"].values():
        for ex in block["exemplars"]:
            ex["lineage"] = lineage_by_ref.get(
                (ex["dispatch"], ex["member_index"]))

    # Fleet-wide lineage tails plus per-kind and per-regime breakdowns.
    kind_spans: Dict[str, List[Dict[str, object]]] = {}
    regime_spans: Dict[str, List[Dict[str, object]]] = {}
    for pos, i in enumerate(member_order):
        kind = scenarios[i].kind
        regime = kind if kind in DELAY_KINDS else "no_delay"
        kind_spans.setdefault(kind, []).extend(lineage_members[pos])
        regime_spans.setdefault(regime, []).extend(lineage_members[pos])
    lineage_block = lineage_lib.lineage_summary(
        [sp for spans in lineage_members for sp in spans])
    lineage_block["by_kind"] = {
        k: lineage_lib.lineage_summary(v)
        for k, v in sorted(kind_spans.items())}
    lineage_block["by_regime"] = {
        k: lineage_lib.lineage_summary(v)
        for k, v in sorted(regime_spans.items())}

    progress.emit({"record": "campaign", "clusters_total": total,
                   "dispatches": len(timeline),
                   "wall_s": round(wall_s, 6),
                   "spot_failures": spot["failed"],
                   "anomalies": dict(anomalies),
                   "flagged_members": triage["flagged_members"]})
    progress.close()
    if writer is not None:
        writer.write(trace_path)

    def _agg_compiles(mode):
        """Sum per-pool AOT compile costs for one mode; None when no
        pool of that mode compiled (mirrors the old one-executable
        ``compile_info[mode]`` shape for schema continuity)."""
        rows = [p for p in pool_compiles if p["mode"] == mode]
        if not rows:
            return None
        agg: Dict[str, object] = {}
        for key in rows[0]:
            if key in ("pool_id", "mode"):
                continue
            vals = [r[key] for r in rows]
            if any(v is None for v in vals):
                agg[key] = None
            elif all(isinstance(v, (int, float)) for v in vals):
                agg[key] = sum(vals)
            else:
                agg[key] = vals[0]
        return agg

    compile_info: Dict[str, object] = {
        "shared": _agg_compiles("shared"),
        "per_receiver": _agg_compiles("per_receiver"),
        "pools": pool_compiles,
    }

    pool_blocks = []
    for pool in pools:
        pkinds: Dict[str, int] = {}
        for i in pool["members"]:
            k = scenarios[i].kind
            pkinds[k] = pkinds.get(k, 0) + 1
        pool_blocks.append({
            "pool_id": pool["pool_id"],
            "mode": pool["mode"],
            "members": len(pool["members"]),
            "dispatches": -(-len(pool["members"]) // pool["fleet_size"]),
            "fleet_size": pool["fleet_size"],
            "kinds": dict(sorted(pkinds.items())),
            "shape": _pool_shape_dict(pool["mode"], pool["shape"]),
        })

    rx_kinds: Dict[str, int] = {}
    for i in rx_idx:
        k = scenarios[i].kind
        rx_kinds[k] = rx_kinds.get(k, 0) + 1
    rx_capacity = max(rx_settings.capacity, cfg.n)
    rx_member_bytes = receiver_mod.receiver_state_bytes(
        rx_capacity, base.K, ring_depth=base.delivery_ring_depth)
    if base.rx_kernel != "xla":
        from rapid_tpu.engine import rx_packed

        rx_member_bytes = rx_packed.bundle_state_bytes(
            rx_capacity, rx_settings)
    per_receiver = {
        "enabled": per_rx,
        "members": len(rx_idx),
        "dispatches": rx_dispatches,
        "fleet_size": fr,
        "capacity": rx_capacity,
        "capacity_cap": base.receiver_capacity_cap,
        "ring_depth": base.delivery_ring_depth,
        "rx_kernel": base.rx_kernel,
        "member_state_bytes": rx_member_bytes,
        "member_state_bytes_unpacked": receiver_mod.receiver_state_bytes(
            rx_capacity, base.K, ring_depth=base.delivery_ring_depth),
        "kinds": dict(sorted(rx_kinds.items())),
    }

    return {
        "bench": "engine_tick",
        "scenario": "fleet",
        "schema_version": SCHEMA_VERSION,
        "platform": jax.default_backend(),
        "n": cfg.n,
        "k": settings.K,
        "capacity": c,
        "ticks": cfg.ticks,
        "clusters": total,
        "fleet_size": f,
        "dispatches": dispatches,
        "boot_s": boot_s,
        "wall_s": wall_s,
        "fold_s": fold_s,
        "compile_s": compile_total,
        "device_busy_s": device_busy_s,
        "host_blocked_s": host_blocked_s,
        "spot_check_s": spot_s,
        "total_s": wall_s + spot_s,
        "ticks_per_sec": _rate(total * cfg.ticks, wall_s),
        "rounds_per_sec": _rate(merged.decisions, wall_s),
        "clusters_per_sec": _rate(total, wall_s),
        "announcements": merged.announcements,
        "decisions": merged.decisions,
        "telemetry": merged.as_dict(),
        "dispatch_timeline": timeline,
        "observatory": {
            "host_blocked_s": host_blocked_s,
            "device_busy_s": device_busy_s,
            "compile_s": compile_total,
            "host_blocked_frac": (host_blocked_s / wall_s
                                  if wall_s >= MIN_MEASURABLE_WALL_S
                                  else None),
            "device_busy_frac": (device_busy_s / wall_s
                                 if wall_s >= MIN_MEASURABLE_WALL_S
                                 else None),
            # What a perfect double-buffer (lower/stack dispatch d+1
            # while d executes) could hide: the smaller of the two
            # overlappable walls.
            "overlap_headroom_s": min(host_blocked_s, device_busy_s),
            "min_measurable_wall_s": MIN_MEASURABLE_WALL_S,
            "compile": compile_info,
            "pipeline": {
                "enabled": cfg.pipeline,
                "max_in_flight": depth,
                "peak_in_flight": peak_in_flight,
            },
        },
        "campaign": {
            "seed": cfg.seed,
            "protocol_variant": cfg.protocol_variant,
            "clusters": total,
            # Replay self-containment (schema v8): everything
            # ``rapid_tpu.replay`` needs to reconstruct the sampled
            # schedules and the dispatch plan from this block alone.
            "n": cfg.n,
            "ticks": cfg.ticks,
            "headroom": cfg.headroom,
            "weights": dataclasses.asdict(
                cfg.weights or DEFAULT_SCENARIO_WEIGHTS),
            "flight_recorder": cfg.flight_recorder,
            "fleet_size": f,
            "dispatches": dispatches,
            "scenario_kinds": dict(sorted(kinds.items())),
            "pools": pool_blocks,
            "per_receiver": per_receiver,
            "spot_checks": spot,
            "distributions": dists,
            "delay_regimes": delay_regimes,
            "triage": triage,
            "lineage": lineage_block,
        },
    }


def run_tournament(cfg: CampaignConfig, variants: List[str], *,
                   trace_path: Optional[str] = None,
                   progress_path: Optional[str] = None
                   ) -> Dict[str, object]:
    """A/B tournament: the same campaign once per protocol variant.

    Every variant runs the identical sampled member set — the scenario
    sampler is seeded from ``cfg.seed``/``n``/``ticks``/``weights``
    alone, which ``dataclasses.replace`` leaves untouched — so the
    per-member join below compares each member against *itself* under a
    different wire protocol: same faults, same identities, same scripted
    proposes.

    Returns the first variant's full payload with a
    ``campaign.tournament`` block added: per-variant decide counts /
    fallback members / total messages / nearest-rank decide-tick tails,
    and per-kind win/loss where the earlier first decide wins, any
    decide beats no decide, and equality is a tie. Every field is
    seed-deterministic, so ``scripts/bench_compare.py``'s exact campaign
    gate covers the whole block.
    """
    from rapid_tpu.telemetry.metrics import _dist

    if len(variants) < 2:
        raise ValueError(f"a tournament needs >= 2 variants, "
                         f"got {variants}")
    if len(set(variants)) != len(variants):
        raise ValueError(f"duplicate tournament variants: {variants}")

    payloads: Dict[str, Dict[str, object]] = {}
    stats: Dict[str, List[Dict[str, object]]] = {}
    for v in variants:
        vcfg = dataclasses.replace(cfg, protocol_variant=v)
        rows: List[Dict[str, object]] = []
        # Trace/progress knobs ride the first variant only — they are
        # I/O, not campaign identity.
        first = v == variants[0]
        payloads[v] = run_campaign(
            vcfg, trace_path=trace_path if first else None,
            progress_path=progress_path if first else None,
            member_stats_out=rows)
        stats[v] = rows

    members = [r["member"] for r in stats[variants[0]]]
    for v in variants[1:]:
        assert [r["member"] for r in stats[v]] == members, \
            "tournament variants diverged on the sampled member set"

    per_variant: Dict[str, Dict[str, object]] = {}
    for v in variants:
        rows = stats[v]
        ticks = [r["decide_tick"] for r in rows if r["decided"]]
        per_variant[v] = {
            "decided": sum(r["decided"] for r in rows),
            "fallback_members": sum(r["fallback"] for r in rows),
            "total_messages": sum(r["total_sent"] for r in rows),
            "decide_ticks": _dist(ticks),
            # Schema v12: where each variant spends its latency — the
            # phase-duration tails over every member's lineage spans.
            "lineage": lineage_lib.lineage_summary(
                [sp for r in rows for sp in r["lineage_spans"]]),
        }

    # Per-kind win/loss: rank each member's variants by
    # (undecided-last, first-decide tick); a unique minimum wins, any
    # shared minimum is a tie for that member.
    win_loss: Dict[str, Dict[str, int]] = {}
    by_member: Dict[str, Dict[int, Dict[str, object]]] = {
        v: {r["member"]: r for r in stats[v]} for v in variants}
    for i, ref in zip(members, stats[variants[0]]):
        kind = ref["kind"]
        row = win_loss.setdefault(
            kind, {**{v: 0 for v in variants}, "tie": 0})
        keys = {v: ((0, by_member[v][i]["decide_tick"])
                    if by_member[v][i]["decided"] else (1, 0))
                for v in variants}
        best = min(keys.values())
        winners = [v for v in variants if keys[v] == best]
        if len(winners) == 1:
            row[winners[0]] += 1
        else:
            row["tie"] += 1

    payload = payloads[variants[0]]
    payload["campaign"]["tournament"] = {
        "variants": list(variants),
        "clusters": len(members),
        "per_variant": per_variant,
        "win_loss": dict(sorted(win_loss.items())),
    }
    return payload


def _parse_weights(text: str) -> ScenarioWeights:
    """``crash=1,partition=2,...`` -> ScenarioWeights (missing keys keep
    their defaults)."""
    kw = {}
    for part in text.split(","):
        if not part:
            continue
        key, _, val = part.partition("=")
        kw[key.strip()] = float(val)
    return ScenarioWeights(**kw)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Monte-Carlo fleet campaign over sampled fault "
                    "scenarios (see rapid_tpu/campaign.py docstring)")
    parser.add_argument("--clusters", type=int, default=64,
                        help="sampled clusters (rounded up to a whole "
                             "number of dispatches)")
    parser.add_argument("--n", type=int, default=64,
                        help="initial members per cluster")
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--fleet-size", type=int, default=64,
                        help="clusters per jitted dispatch (F)")
    parser.add_argument("--headroom", type=int, default=16,
                        help="dormant slots per cluster for churn joins")
    parser.add_argument("--spot-checks", type=int, default=0,
                        help="members replayed through the host oracle "
                             "referee (run_adversarial_differential / "
                             "run_receiver_differential)")
    parser.add_argument("--max-spot-failures", type=int, default=0,
                        help="spot-check divergences tolerated before the "
                             "campaign aborts; failures are recorded in "
                             "the payload with forensics artifacts either "
                             "way (default 0: any divergence is fatal)")
    parser.add_argument("--spot-artifacts", type=str, default=None,
                        metavar="DIR",
                        help="directory for divergence forensics JSONL "
                             "artifacts (default: system temp dir)")
    parser.add_argument("--no-per-receiver", action="store_true",
                        help="force partition/flip-flop members onto the "
                             "shared-state fast path (losing the "
                             "device-exact guarantee); latency-family "
                             "members stay per-receiver regardless — the "
                             "shared wire cannot represent delays")
    parser.add_argument("--weights", type=_parse_weights, default=None,
                        metavar="K=W,...",
                        help="scenario mix over "
                             + ",".join(SCENARIO_KINDS)
                             + " (missing kinds keep their defaults), "
                               "e.g. crash=1,partition=2,delay=1,jitter=0")
    parser.add_argument("--variant", type=str, default="rapid",
                        choices=("rapid", "ring", "hier"),
                        help="protocol variant every member runs "
                             "(rapid_tpu.variants): 'rapid' (default), "
                             "'ring' (O(N) ring dissemination), 'hier' "
                             "(two-level group consensus). Non-rapid "
                             "variants run the shared-state path only "
                             "and reject latency-family weights and "
                             "--spot-checks")
    parser.add_argument("--tournament", type=str, default=None,
                        metavar="V1,V2[,...]",
                        help="A/B tournament: run every sampled member "
                             "once per listed variant over identical "
                             "schedules and report the "
                             "campaign.tournament block (e.g. "
                             "'rapid,ring'); overrides --variant")
    parser.add_argument("--out", type=str, default=None,
                        help="write the full payload JSON here")
    parser.add_argument("--trace", type=str, default=None, metavar="FILE",
                        help="write a Chrome/Perfetto trace-event JSON of "
                             "the campaign's dispatch stages (open at "
                             "ui.perfetto.dev)")
    parser.add_argument("--progress", type=str, default=None,
                        metavar="FILE",
                        help="stream a JSONL heartbeat line per completed "
                             "dispatch (and per spot check) to FILE; '-' "
                             "streams to stderr")
    parser.add_argument("--pipeline", dest="pipeline", action="store_true",
                        default=True,
                        help="double-buffer dispatches: lower/stack "
                             "dispatch F+1 on the host while F executes "
                             "on device (default)")
    parser.add_argument("--no-pipeline", dest="pipeline",
                        action="store_false",
                        help="serial driver: fence each dispatch before "
                             "preparing the next (the pre-pipeline "
                             "behaviour; payloads are bit-identical to "
                             "--pipeline in all non-wall fields)")
    parser.add_argument("--no-compile-cache", dest="compile_cache",
                        action="store_false",
                        help="skip the on-disk XLA compilation cache "
                             "(RAPID_TPU_COMPILE_CACHE overrides the "
                             "default ~/.cache/rapid_tpu/xla directory)")
    parser.add_argument("--fleet-shard", type=int, default=None,
                        metavar="D",
                        help="shard each dispatch's fleet axis over D "
                             "devices (P('fleet'), no collectives); "
                             "errors if fewer devices exist")
    parser.add_argument("--flight-recorder", type=int, default=0,
                        metavar="W",
                        help="on-device flight recorder window: carry a "
                             "[W, G] per-tick gauge ring + first-"
                             "occurrence stamps through every member's "
                             "scan and embed the rings of triage-flagged "
                             "exemplars in the payload (0 = compiled "
                             "out, byte-identical member programs)")
    parser.add_argument("--rx-kernel", type=str, default="xla",
                        choices=("xla", "packed", "pallas"),
                        help="per-receiver state layout/kernel: 'xla' "
                             "(dense, default), 'packed' (bit-plane carry "
                             "through the scan), 'pallas' (packed carry + "
                             "pallas deliver/aggregate kernel; interpreted "
                             "off-TPU). Spot-check referees inherit the "
                             "same setting, so exactness gates cover it")
    args = parser.parse_args(argv)

    settings = None
    if args.rx_kernel != "xla":
        settings = Settings(rx_kernel=args.rx_kernel)
    cfg = CampaignConfig(clusters=args.clusters, n=args.n, ticks=args.ticks,
                         seed=args.seed, fleet_size=args.fleet_size,
                         headroom=args.headroom, weights=args.weights,
                         spot_checks=args.spot_checks,
                         per_receiver=not args.no_per_receiver,
                         max_spot_failures=args.max_spot_failures,
                         artifact_dir=args.spot_artifacts,
                         pipeline=args.pipeline,
                         fleet_shard=args.fleet_shard,
                         compile_cache=args.compile_cache,
                         flight_recorder=args.flight_recorder,
                         settings=settings,
                         protocol_variant=args.variant)
    if args.tournament:
        variants = [v.strip() for v in args.tournament.split(",")
                    if v.strip()]
        payload = run_tournament(cfg, variants, trace_path=args.trace,
                                 progress_path=args.progress)
    else:
        payload = run_campaign(cfg, trace_path=args.trace,
                               progress_path=args.progress)
    if args.out:
        from rapid_tpu.telemetry import write_json_artifact

        write_json_artifact(args.out, payload, indent=2)
    # Last stdout line is the machine-readable payload (the bench.py
    # contract); campaigns have no per-view-change rows to elide.
    print(json.dumps(payload), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
