"""Monte-Carlo fault campaigns over a fleet of batched clusters.

The campaign driver samples scenario space with
``faults.sample_adversary_schedule`` (seeded weights over
crash/partition/flip-flop/contested/churn mixes), lowers every draw to a
device ``FleetMember`` (``engine.fleet.lower_schedule``), and runs
``fleet_size`` clusters per jitted dispatch — thousands of independent
clusters complete in one process with a single compile, since every
dispatch shares the batched program shape.

Aggregation goes through the existing telemetry layer: each member's
logs fold into a ``RunSummary`` (``telemetry.metrics.fleet_summaries``),
the fleet aggregate merges with the documented max-vs-total gauge
semantics (``merge_summaries`` / ``schema.GAUGE_SEMANTICS``), and
campaign distributions (ticks-to-decide percentiles, message-complexity
tails, invariant-violation rates) are nearest-rank percentiles over the
per-member summaries — bit-deterministic in the campaign seed.

Exactness: a seeded subset of members (≥1 partition and ≥1 contested /
classic-fallback scenario when the check budget allows) is replayed
host-side through ``diff.run_adversarial_differential``, the per-slot
oracle referee. Churn-mix members are excluded from the spot-check pool
— the referee replays ``AdversarySchedule`` surfaces only; churn
scheduling stays engine-side (see ``engine.churn``). This referee loop
is the only host-side part of a campaign.

CLI::

    python -m rapid_tpu.campaign --clusters 1024 --n 64 --ticks 240 \
        --seed 0 --fleet-size 64 --spot-checks 8 --out campaign.json
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import random
import sys
import time
from typing import Dict, List, Optional, Tuple

from rapid_tpu import hashing
from rapid_tpu.faults import (DEFAULT_SCENARIO_WEIGHTS, SampledScenario,
                              ScenarioWeights, sample_adversary_schedule)
from rapid_tpu.settings import Settings

__all__ = ["CampaignConfig", "run_campaign", "main"]

#: Spot-check kinds the acceptance gate requires when the budget allows:
#: a partition (link-masked FD path) and a contested split (classic-Paxos
#: fallback on both sides of the differential).
REQUIRED_SPOT_KINDS = ("partition", "contested")


@dataclasses.dataclass(frozen=True)
class CampaignConfig:
    """Knobs of one campaign; everything downstream is derived from these
    (same config => bit-identical aggregates and distributions)."""

    clusters: int = 64
    n: int = 64
    ticks: int = 240
    seed: int = 0
    fleet_size: int = 64
    headroom: int = 16          # dormant slots per cluster for churn joins
    weights: Optional[ScenarioWeights] = None
    spot_checks: int = 0
    settings: Optional[Settings] = None


def _member_seed(cfg: CampaignConfig, idx: int) -> int:
    """Deterministic per-member scenario seed from the campaign seed."""
    return hashing.hash64(idx, seed=cfg.seed & hashing.MASK64) & 0x7FFFFFFF


def _sample_member(cfg: CampaignConfig, settings: Settings, idx: int):
    """Draw member ``idx``'s scenario and lower it to the device."""
    from rapid_tpu.engine import churn as churn_mod
    from rapid_tpu.engine.fleet import lower_schedule

    seed = _member_seed(cfg, idx)
    sc = sample_adversary_schedule(cfg.n, seed, cfg.ticks,
                                   cfg.weights or DEFAULT_SCENARIO_WEIGHTS)
    churn = id_fps = None
    if sc.wants_churn and cfg.headroom >= 2:
        rng = random.Random(seed ^ 0xC4B0)
        burst = min(cfg.headroom, rng.choice((2, 4, 8)))
        churn, id_fps, _ = churn_mod.synthetic_churn_schedule(
            cfg.n + cfg.headroom, cfg.n, settings,
            start=rng.randint(5, 25), burst=burst)
    member = lower_schedule(sc.schedule, settings, churn=churn,
                            id_fps=id_fps)
    return member, sc


def _spot_check(cfg: CampaignConfig, scenarios: List[SampledScenario],
                referee_settings: Settings) -> Dict[str, object]:
    """Replay a seeded member subset through the host oracle referee.

    ``run_adversarial_differential`` raises (with forensics) on any
    per-slot divergence, so a campaign either reports every check passed
    or dies loudly. Members whose scenario wants churn are ineligible
    (the referee replays fault surfaces only); if a required kind is
    missing from the eligible pool, a fresh forced scenario of that kind
    is synthesized from the campaign seed and checked as member ``-1``.
    """
    from rapid_tpu.engine.diff import run_adversarial_differential

    requested = cfg.spot_checks
    block: Dict[str, object] = {"requested": requested, "run": 0,
                                "passed": 0, "members": []}
    if requested <= 0:
        return block
    rng = random.Random(cfg.seed ^ 0x5EED)
    eligible = [i for i, sc in enumerate(scenarios) if not sc.wants_churn]
    chosen: List[Tuple[int, SampledScenario]] = []
    used = set()
    for kind in REQUIRED_SPOT_KINDS[:requested]:
        pool = [i for i in eligible
                if scenarios[i].kind == kind and i not in used]
        if pool:
            i = rng.choice(pool)
            used.add(i)
            chosen.append((i, scenarios[i]))
        else:  # tiny campaign without this kind: force one
            forced_seed = hashing.hash64(
                len(chosen), seed=(cfg.seed ^ 0xF0CE) & hashing.MASK64
            ) & 0x7FFFFFFF
            weights = ScenarioWeights(
                **{k: (1.0 if k == kind else 0.0)
                   for k in ("crash", "partition", "flip_flop",
                             "contested", "churn")})
            forced = sample_adversary_schedule(cfg.n, forced_seed,
                                               cfg.ticks, weights)
            chosen.append((-1, forced))
    rest = [i for i in eligible if i not in used]
    rng.shuffle(rest)
    for i in rest[:max(0, requested - len(chosen))]:
        chosen.append((i, scenarios[i]))

    for idx, sc in chosen:
        result = run_adversarial_differential(sc.schedule, cfg.ticks,
                                              referee_settings)
        result.assert_identical()
        block["run"] += 1
        block["passed"] += 1
        block["members"].append({"member": idx, "kind": sc.kind,
                                 "seed": sc.schedule.seed})
    return block


def run_campaign(cfg: CampaignConfig) -> Dict[str, object]:
    """Run one campaign; returns a schema-v3 bench run payload.

    The payload validates as an ``engine_tick`` run (``telemetry`` is the
    fleet-merged ``RunSummary``) and additionally carries the
    ``campaign`` block: scenario-kind counts, spot-check results, and
    nearest-rank distributions over per-member summaries.
    ``ticks_per_sec`` is aggregate cluster-ticks per second across all
    dispatches (compile included — campaigns are one-shot programs).
    """
    import jax

    from rapid_tpu.engine.fleet import fleet_simulate, stack_members
    from rapid_tpu.telemetry.metrics import (fleet_summaries,
                                             merge_summaries,
                                             summary_distributions)
    from rapid_tpu.telemetry.schema import SCHEMA_VERSION

    base = cfg.settings or Settings()
    c = cfg.n + cfg.headroom
    settings = base if base.capacity == c else base.with_(capacity=c)
    referee_settings = base if base.capacity == 0 else base.with_(capacity=0)
    f = max(1, cfg.fleet_size)
    dispatches = -(-cfg.clusters // f)
    total = dispatches * f

    t0 = time.perf_counter()
    sampled = [_sample_member(cfg, settings, i) for i in range(total)]
    scenarios = [sc for _, sc in sampled]
    boot_s = time.perf_counter() - t0

    summaries = []
    t0 = time.perf_counter()
    fold_s = 0.0
    for d in range(dispatches):
        fleet = stack_members([m for m, _ in
                               sampled[d * f:(d + 1) * f]])
        finals, logs = fleet_simulate(fleet, cfg.ticks, settings)
        jax.block_until_ready(finals)
        tf = time.perf_counter()
        summaries += fleet_summaries(logs)
        fold_s += time.perf_counter() - tf
    wall_s = time.perf_counter() - t0 - fold_s

    merged = merge_summaries(summaries)
    dists = summary_distributions(summaries)
    kinds: Dict[str, int] = {}
    for sc in scenarios:
        kinds[sc.kind] = kinds.get(sc.kind, 0) + 1

    t0 = time.perf_counter()
    spot = _spot_check(cfg, scenarios, referee_settings)
    spot_s = time.perf_counter() - t0

    return {
        "bench": "engine_tick",
        "scenario": "fleet",
        "schema_version": SCHEMA_VERSION,
        "platform": jax.default_backend(),
        "n": cfg.n,
        "k": settings.K,
        "capacity": c,
        "ticks": cfg.ticks,
        "clusters": total,
        "fleet_size": f,
        "dispatches": dispatches,
        "boot_s": boot_s,
        "wall_s": wall_s,
        "fold_s": fold_s,
        "spot_check_s": spot_s,
        "ticks_per_sec": total * cfg.ticks / wall_s if wall_s else 0.0,
        "rounds_per_sec": merged.decisions / wall_s if wall_s else 0.0,
        "announcements": merged.announcements,
        "decisions": merged.decisions,
        "telemetry": merged.as_dict(),
        "campaign": {
            "seed": cfg.seed,
            "clusters": total,
            "fleet_size": f,
            "dispatches": dispatches,
            "scenario_kinds": dict(sorted(kinds.items())),
            "spot_checks": spot,
            "distributions": dists,
        },
    }


def _parse_weights(text: str) -> ScenarioWeights:
    """``crash=1,partition=2,...`` -> ScenarioWeights (missing keys keep
    their defaults)."""
    kw = {}
    for part in text.split(","):
        if not part:
            continue
        key, _, val = part.partition("=")
        kw[key.strip()] = float(val)
    return ScenarioWeights(**kw)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Monte-Carlo fleet campaign over sampled fault "
                    "scenarios (see rapid_tpu/campaign.py docstring)")
    parser.add_argument("--clusters", type=int, default=64,
                        help="sampled clusters (rounded up to a whole "
                             "number of dispatches)")
    parser.add_argument("--n", type=int, default=64,
                        help="initial members per cluster")
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--fleet-size", type=int, default=64,
                        help="clusters per jitted dispatch (F)")
    parser.add_argument("--headroom", type=int, default=16,
                        help="dormant slots per cluster for churn joins")
    parser.add_argument("--spot-checks", type=int, default=0,
                        help="members replayed through the host oracle "
                             "referee (run_adversarial_differential)")
    parser.add_argument("--weights", type=_parse_weights, default=None,
                        metavar="K=W,...",
                        help="scenario mix, e.g. crash=1,partition=2,"
                             "flip_flop=0,contested=1,churn=1")
    parser.add_argument("--out", type=str, default=None,
                        help="write the full payload JSON here")
    args = parser.parse_args(argv)

    cfg = CampaignConfig(clusters=args.clusters, n=args.n, ticks=args.ticks,
                         seed=args.seed, fleet_size=args.fleet_size,
                         headroom=args.headroom, weights=args.weights,
                         spot_checks=args.spot_checks)
    payload = run_campaign(cfg)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
    # Last stdout line is the machine-readable payload (the bench.py
    # contract); campaigns have no per-view-change rows to elide.
    print(json.dumps(payload), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
