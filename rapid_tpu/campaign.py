"""Monte-Carlo fault campaigns over a fleet of batched clusters.

The campaign driver samples scenario space with
``faults.sample_adversary_schedule`` (seeded weights over
crash/partition/flip-flop/contested/churn mixes), lowers every draw to a
device ``FleetMember`` (``engine.fleet.lower_schedule``), and runs
``fleet_size`` clusters per jitted dispatch — thousands of independent
clusters complete in one process with a single compile, since every
dispatch shares the batched program shape.

Aggregation goes through the existing telemetry layer: each member's
logs fold into a ``RunSummary`` (``telemetry.metrics.fleet_summaries``),
the fleet aggregate merges with the documented max-vs-total gauge
semantics (``merge_summaries`` / ``schema.GAUGE_SEMANTICS``), and
campaign distributions (ticks-to-decide percentiles, message-complexity
tails, invariant-violation rates) are nearest-rank percentiles over the
per-member summaries — bit-deterministic in the campaign seed.

Exactness: partition and flip-flop members are dispatched in
**per-receiver** mode (``engine.receiver`` via
``fleet.lower_receiver_schedule`` / ``receiver_fleet_simulate``), so
their reported event streams and counters are *device-exact* under link
faults — no host replay is load-bearing for them. Crash / contested /
churn members keep the shared-state fast path, which is exact for those
kinds. The quadratic per-receiver state is budgeted up front
(``fleet.check_receiver_budget``): an oversized fleet raises a
structured ``ReceiverBudgetError`` naming the measured per-member bytes
before any device allocation, never an OOM mid-campaign.

Spot checks are belt-and-suspenders on top of that: a seeded subset of
members (≥1 partition and ≥1 contested / classic-fallback scenario when
the check budget allows) is replayed host-side through the per-slot
oracle referee — ``diff.run_receiver_differential`` for per-receiver
kinds, ``diff.run_adversarial_differential`` for the rest. Churn-mix
members are excluded from the spot-check pool (the referee replays
``AdversarySchedule`` surfaces only; churn scheduling stays
engine-side, see ``engine.churn``). A diverging check no longer kills
the campaign outright: each failure writes a JSONL forensics artifact
and lands as a structured record in the payload, and the run aborts
only when failures exceed ``--max-spot-failures`` (default 0 keeps the
old strictness). This referee loop is the only host-side part of a
campaign.

CLI::

    python -m rapid_tpu.campaign --clusters 1024 --n 64 --ticks 240 \
        --seed 0 --fleet-size 64 --spot-checks 8 --out campaign.json
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from rapid_tpu import hashing
from rapid_tpu.faults import (DEFAULT_SCENARIO_WEIGHTS, SampledScenario,
                              ScenarioWeights, sample_adversary_schedule)
from rapid_tpu.settings import Settings

__all__ = ["CampaignConfig", "run_campaign", "main"]

#: Spot-check kinds the acceptance gate requires when the budget allows:
#: a partition (link-masked FD path) and a contested split (classic-Paxos
#: fallback on both sides of the differential).
REQUIRED_SPOT_KINDS = ("partition", "contested")


@dataclasses.dataclass(frozen=True)
class CampaignConfig:
    """Knobs of one campaign; everything downstream is derived from these
    (same config => bit-identical aggregates and distributions)."""

    clusters: int = 64
    n: int = 64
    ticks: int = 240
    seed: int = 0
    fleet_size: int = 64
    headroom: int = 16          # dormant slots per cluster for churn joins
    weights: Optional[ScenarioWeights] = None
    spot_checks: int = 0
    settings: Optional[Settings] = None
    # Route partition/flip-flop members through the per-receiver engine
    # (device-exact under link faults); False forces every member onto
    # the shared-state fast path (pre-exactness behaviour, cheap).
    per_receiver: bool = True
    # Spot-check failures tolerated before the campaign aborts; each
    # failure writes a forensics artifact and a payload record either
    # way. 0 == any divergence is fatal (the historical contract).
    max_spot_failures: int = 0
    # Where divergence artifacts land (default: the system temp dir).
    artifact_dir: Optional[str] = None


def _receiver_eligible(sc: SampledScenario) -> bool:
    """Per-receiver dispatch eligibility: link-fault-only members.

    Scripted proposes and churn are shared-path features (the
    per-receiver envelope is crash + link windows, see
    ``engine.receiver``); crash-only members gain nothing from the
    quadratic state and stay on the fast path too.
    """
    return (sc.kind in ("partition", "flip_flop")
            and not sc.wants_churn and not sc.schedule.proposes)


def _member_seed(cfg: CampaignConfig, idx: int) -> int:
    """Deterministic per-member scenario seed from the campaign seed."""
    return hashing.hash64(idx, seed=cfg.seed & hashing.MASK64) & 0x7FFFFFFF


def _sample_scenario(cfg: CampaignConfig, idx: int) -> SampledScenario:
    """Draw member ``idx``'s scenario (seeded by the campaign seed)."""
    return sample_adversary_schedule(cfg.n, _member_seed(cfg, idx),
                                     cfg.ticks,
                                     cfg.weights or DEFAULT_SCENARIO_WEIGHTS)


def _lower_shared(cfg: CampaignConfig, settings: Settings, idx: int,
                  sc: SampledScenario):
    """Lower one shared-state member (the pre-existing fast path)."""
    from rapid_tpu.engine import churn as churn_mod
    from rapid_tpu.engine.fleet import lower_schedule

    seed = _member_seed(cfg, idx)
    churn = id_fps = None
    if sc.wants_churn and cfg.headroom >= 2:
        rng = random.Random(seed ^ 0xC4B0)
        burst = min(cfg.headroom, rng.choice((2, 4, 8)))
        churn, id_fps, _ = churn_mod.synthetic_churn_schedule(
            cfg.n + cfg.headroom, cfg.n, settings,
            start=rng.randint(5, 25), burst=burst)
    return lower_schedule(sc.schedule, settings, churn=churn,
                          id_fps=id_fps)


def _chunks(seq: List[int], size: int) -> List[List[int]]:
    return [seq[i:i + size] for i in range(0, len(seq), size)]


def _spot_check(cfg: CampaignConfig, scenarios: List[SampledScenario],
                referee_settings: Settings) -> Dict[str, object]:
    """Replay a seeded member subset through the host oracle referee.

    Per-receiver-eligible kinds replay through
    ``run_receiver_differential`` (the same engine that ran the member
    on device — belt-and-suspenders on the device-exact claim); the
    rest through ``run_adversarial_differential``. Members whose
    scenario wants churn are ineligible (the referee replays fault
    surfaces only); if a required kind is missing from the eligible
    pool, a fresh forced scenario of that kind is synthesized from the
    campaign seed and checked as member ``-1``.

    A divergence no longer dies in place: the failing check writes a
    JSONL forensics artifact, lands as a structured member record
    (``passed=False`` + error + artifact path), and the campaign aborts
    only once failures exceed ``cfg.max_spot_failures`` — whose default
    of 0 preserves the historical any-divergence-is-fatal contract.
    """
    from rapid_tpu.engine.diff import (run_adversarial_differential,
                                       run_receiver_differential)
    from rapid_tpu.engine.receiver import ReceiverEnvelopeError
    from rapid_tpu.telemetry.forensics import DivergenceError

    requested = cfg.spot_checks
    block: Dict[str, object] = {"requested": requested, "run": 0,
                                "passed": 0, "failed": 0,
                                "max_failures": cfg.max_spot_failures,
                                "members": []}
    if requested <= 0:
        return block
    rng = random.Random(cfg.seed ^ 0x5EED)
    eligible = [i for i, sc in enumerate(scenarios) if not sc.wants_churn]
    chosen: List[Tuple[int, SampledScenario]] = []
    used = set()
    for kind in REQUIRED_SPOT_KINDS[:requested]:
        pool = [i for i in eligible
                if scenarios[i].kind == kind and i not in used]
        if pool:
            i = rng.choice(pool)
            used.add(i)
            chosen.append((i, scenarios[i]))
        else:  # tiny campaign without this kind: force one
            forced_seed = hashing.hash64(
                len(chosen), seed=(cfg.seed ^ 0xF0CE) & hashing.MASK64
            ) & 0x7FFFFFFF
            weights = ScenarioWeights(
                **{k: (1.0 if k == kind else 0.0)
                   for k in ("crash", "partition", "flip_flop",
                             "contested", "churn")})
            forced = sample_adversary_schedule(cfg.n, forced_seed,
                                               cfg.ticks, weights)
            chosen.append((-1, forced))
    rest = [i for i in eligible if i not in used]
    rng.shuffle(rest)
    for i in rest[:max(0, requested - len(chosen))]:
        chosen.append((i, scenarios[i]))

    art_dir = cfg.artifact_dir or tempfile.gettempdir()
    for idx, sc in chosen:
        per_rx = cfg.per_receiver and _receiver_eligible(sc)
        runner = run_receiver_differential if per_rx \
            else run_adversarial_differential
        artifact = os.path.join(
            art_dir, f"rapid_tpu_spot_m{idx}_{sc.kind}_"
                     f"{sc.schedule.seed}.jsonl")
        record: Dict[str, object] = {
            "member": idx, "kind": sc.kind, "seed": sc.schedule.seed,
            "mode": "per_receiver" if per_rx else "shared",
            "passed": True, "artifact": None, "error": None}
        block["run"] += 1
        try:
            result = runner(sc.schedule, cfg.ticks, referee_settings)
            result.assert_identical(artifact=artifact)
            block["passed"] += 1
        except (DivergenceError, ReceiverEnvelopeError) as err:
            record["passed"] = False
            record["artifact"] = artifact if os.path.exists(artifact) \
                else None
            record["error"] = str(err).splitlines()[0]
            block["failed"] += 1
        block["members"].append(record)
    if block["failed"] > cfg.max_spot_failures:
        bad = [m for m in block["members"] if not m["passed"]]
        raise RuntimeError(
            f"{block['failed']} spot-check divergence(s) exceed "
            f"--max-spot-failures={cfg.max_spot_failures}: "
            + "; ".join(
                f"member {m['member']} ({m['kind']}, seed {m['seed']}): "
                f"{m['error']}" + (f" [forensics: {m['artifact']}]"
                                   if m["artifact"] else "")
                for m in bad))
    return block


def run_campaign(cfg: CampaignConfig) -> Dict[str, object]:
    """Run one campaign; returns a schema-v4 bench run payload.

    The payload validates as an ``engine_tick`` run (``telemetry`` is the
    fleet-merged ``RunSummary``) and additionally carries the
    ``campaign`` block: scenario-kind counts, spot-check results, and
    nearest-rank distributions over per-member summaries.
    ``ticks_per_sec`` is aggregate cluster-ticks per second across all
    dispatches (compile included — campaigns are one-shot programs).
    """
    import jax

    from rapid_tpu.engine import receiver as receiver_mod
    from rapid_tpu.engine.fleet import (check_receiver_budget,
                                        fleet_simulate,
                                        lower_receiver_schedule,
                                        receiver_fleet_simulate,
                                        stack_members,
                                        stack_receiver_members)
    from rapid_tpu.telemetry.metrics import (fleet_summaries,
                                             merge_summaries,
                                             summarize,
                                             summary_distributions)
    from rapid_tpu.telemetry.schema import SCHEMA_VERSION

    base = cfg.settings or Settings()
    c = cfg.n + cfg.headroom
    settings = base if base.capacity == c else base.with_(capacity=c)
    referee_settings = base if base.capacity == 0 else base.with_(capacity=0)
    # Per-receiver members never churn, so they boot without the churn
    # headroom — the quadratic state is sized to N, not N + headroom.
    rx_settings = base if base.capacity == cfg.n \
        else base.with_(capacity=cfg.n)
    f = max(1, cfg.fleet_size)
    dispatches = -(-cfg.clusters // f)
    total = dispatches * f

    t0 = time.perf_counter()
    scenarios = [_sample_scenario(cfg, i) for i in range(total)]
    rx_idx = [i for i, sc in enumerate(scenarios)
              if cfg.per_receiver and _receiver_eligible(sc)]
    sh_idx = [i for i in range(total) if i not in set(rx_idx)]
    # Budget refusal first: an oversized per-receiver fleet raises the
    # structured ReceiverBudgetError before any member is lowered.
    fr = min(f, len(rx_idx)) if rx_idx else 0
    if rx_idx:
        check_receiver_budget(max(rx_settings.capacity, cfg.n), fr,
                              rx_settings)
    sh_members = {i: _lower_shared(cfg, settings, i, scenarios[i])
                  for i in sh_idx}
    rx_members = {i: lower_receiver_schedule(scenarios[i].schedule,
                                             rx_settings, fleet_size=fr)
                  for i in rx_idx}
    boot_s = time.perf_counter() - t0

    summaries = []
    rx_dispatches = 0
    t0 = time.perf_counter()
    fold_s = 0.0
    fs = min(f, len(sh_idx)) if sh_idx else 0
    for chunk in _chunks(sh_idx, fs) if fs else []:
        # Pad a trailing partial chunk by cycling its own members so
        # every shared dispatch keeps one batched program shape; padded
        # summaries are dropped below.
        padded = chunk + [chunk[i % len(chunk)]
                          for i in range(fs - len(chunk))]
        fleet = stack_members([sh_members[i] for i in padded])
        finals, logs = fleet_simulate(fleet, cfg.ticks, settings)
        jax.block_until_ready(finals)
        tf = time.perf_counter()
        summaries += fleet_summaries(logs)[:len(chunk)]
        fold_s += time.perf_counter() - tf
    for chunk in _chunks(rx_idx, fr) if fr else []:
        padded = chunk + [chunk[i % len(chunk)]
                          for i in range(fr - len(chunk))]
        fleet = stack_receiver_members([rx_members[i] for i in padded])
        finals, logs = receiver_fleet_simulate(fleet, cfg.ticks,
                                               rx_settings)
        jax.block_until_ready(finals)
        rx_dispatches += 1
        tf = time.perf_counter()
        for j in range(len(chunk)):
            mrs = jax.tree_util.tree_map(lambda x, j=j: x[j], finals)
            mlog = jax.tree_util.tree_map(lambda x, j=j: x[j], logs)
            # A nonzero envelope flag would void the device-exact claim
            # for this member; eligibility keeps schedules inside the
            # envelope, so this raising means an engine bug.
            receiver_mod.check_flags(mrs.flags)
            run = receiver_mod.receiver_run_payload(mrs, mlog, cfg.n,
                                                    cfg.ticks)
            summaries.append(summarize(run.metrics()))
        fold_s += time.perf_counter() - tf
    wall_s = time.perf_counter() - t0 - fold_s

    merged = merge_summaries(summaries)
    dists = summary_distributions(summaries)
    kinds: Dict[str, int] = {}
    for sc in scenarios:
        kinds[sc.kind] = kinds.get(sc.kind, 0) + 1

    t0 = time.perf_counter()
    spot = _spot_check(cfg, scenarios, referee_settings)
    spot_s = time.perf_counter() - t0

    rx_kinds: Dict[str, int] = {}
    for i in rx_idx:
        k = scenarios[i].kind
        rx_kinds[k] = rx_kinds.get(k, 0) + 1
    rx_capacity = max(rx_settings.capacity, cfg.n)
    per_receiver = {
        "enabled": cfg.per_receiver,
        "members": len(rx_idx),
        "dispatches": rx_dispatches,
        "fleet_size": fr,
        "capacity": rx_capacity,
        "capacity_cap": base.receiver_capacity_cap,
        "member_state_bytes": receiver_mod.receiver_state_bytes(
            rx_capacity, base.K),
        "kinds": dict(sorted(rx_kinds.items())),
    }

    return {
        "bench": "engine_tick",
        "scenario": "fleet",
        "schema_version": SCHEMA_VERSION,
        "platform": jax.default_backend(),
        "n": cfg.n,
        "k": settings.K,
        "capacity": c,
        "ticks": cfg.ticks,
        "clusters": total,
        "fleet_size": f,
        "dispatches": dispatches,
        "boot_s": boot_s,
        "wall_s": wall_s,
        "fold_s": fold_s,
        "spot_check_s": spot_s,
        "ticks_per_sec": total * cfg.ticks / wall_s if wall_s else 0.0,
        "rounds_per_sec": merged.decisions / wall_s if wall_s else 0.0,
        "announcements": merged.announcements,
        "decisions": merged.decisions,
        "telemetry": merged.as_dict(),
        "campaign": {
            "seed": cfg.seed,
            "clusters": total,
            "fleet_size": f,
            "dispatches": dispatches,
            "scenario_kinds": dict(sorted(kinds.items())),
            "per_receiver": per_receiver,
            "spot_checks": spot,
            "distributions": dists,
        },
    }


def _parse_weights(text: str) -> ScenarioWeights:
    """``crash=1,partition=2,...`` -> ScenarioWeights (missing keys keep
    their defaults)."""
    kw = {}
    for part in text.split(","):
        if not part:
            continue
        key, _, val = part.partition("=")
        kw[key.strip()] = float(val)
    return ScenarioWeights(**kw)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Monte-Carlo fleet campaign over sampled fault "
                    "scenarios (see rapid_tpu/campaign.py docstring)")
    parser.add_argument("--clusters", type=int, default=64,
                        help="sampled clusters (rounded up to a whole "
                             "number of dispatches)")
    parser.add_argument("--n", type=int, default=64,
                        help="initial members per cluster")
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--fleet-size", type=int, default=64,
                        help="clusters per jitted dispatch (F)")
    parser.add_argument("--headroom", type=int, default=16,
                        help="dormant slots per cluster for churn joins")
    parser.add_argument("--spot-checks", type=int, default=0,
                        help="members replayed through the host oracle "
                             "referee (run_adversarial_differential / "
                             "run_receiver_differential)")
    parser.add_argument("--max-spot-failures", type=int, default=0,
                        help="spot-check divergences tolerated before the "
                             "campaign aborts; failures are recorded in "
                             "the payload with forensics artifacts either "
                             "way (default 0: any divergence is fatal)")
    parser.add_argument("--spot-artifacts", type=str, default=None,
                        metavar="DIR",
                        help="directory for divergence forensics JSONL "
                             "artifacts (default: system temp dir)")
    parser.add_argument("--no-per-receiver", action="store_true",
                        help="force every member onto the shared-state "
                             "fast path (partition/flip-flop members "
                             "lose the device-exact guarantee)")
    parser.add_argument("--weights", type=_parse_weights, default=None,
                        metavar="K=W,...",
                        help="scenario mix, e.g. crash=1,partition=2,"
                             "flip_flop=0,contested=1,churn=1")
    parser.add_argument("--out", type=str, default=None,
                        help="write the full payload JSON here")
    args = parser.parse_args(argv)

    cfg = CampaignConfig(clusters=args.clusters, n=args.n, ticks=args.ticks,
                         seed=args.seed, fleet_size=args.fleet_size,
                         headroom=args.headroom, weights=args.weights,
                         spot_checks=args.spot_checks,
                         per_receiver=not args.no_per_receiver,
                         max_spot_failures=args.max_spot_failures,
                         artifact_dir=args.spot_artifacts)
    payload = run_campaign(cfg)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
    # Last stdout line is the machine-readable payload (the bench.py
    # contract); campaigns have no per-view-change rows to elide.
    print(json.dumps(payload), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
