"""K-ring expander membership view (host oracle).

Mirrors the semantics of the reference MembershipView
(rapid/src/main/java/com/vrg/rapid/MembershipView.java):

- K logical rings, each ordering all members by a seeded 64-bit hash of the
  endpoint (reference: seeded XXHash, MembershipView.java:47,562-587; here the
  shared splitmix64 of rapid_tpu.hashing, with (hash, endpoint-id) as the sort
  key so the order is total even under hash collisions).
- Observers of a member = its successor on each ring
  (MembershipView.java:234-257); subjects = predecessor on each ring
  (:267-282,308-322).
- Expected observers of a *joiner* (not yet in the rings) = the predecessors
  of its would-be position (:292-303) — note the reference deliberately uses
  predecessors here, not successors; these gatekeepers send the UP alerts.
- Join safety: reject reused hostnames and reused node identifiers
  (:100-115); identifiers are remembered forever (:51).
- Configuration identity: a 64-bit fingerprint of (identifiers seen, current
  members). The reference uses an order-dependent 37x polynomial
  (:540-556); since both operand sequences are themselves functions of the
  *sets* (ids sorted, endpoints in ring-0 order), an order-independent sum of
  per-element fingerprints finalized with splitmix64 carries the same
  information and is one reduction on TPU. The oracle and the kernel engine
  share this formula (rapid_tpu.hashing / engine state).

The rings are represented once: a single sorted list per ring of
(ring_key, endpoint_id, Endpoint). N here is oracle-scale (<= a few thousand);
insertion is O(N) via bisect which is plenty.
"""
from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

from rapid_tpu import hashing
from rapid_tpu.types import Endpoint, JoinStatusCode, NodeId

MASK64 = hashing.MASK64

# Seeds for the various hash domains (arbitrary but fixed).
_SEED_ID_HIGH = 0x6964_6869
_SEED_ID_LOW = 0x6964_6C6F
_SEED_MEMBER = 0x6D656D62


def endpoint_uid(endpoint: Endpoint) -> int:
    """64-bit identity of an endpoint (host-side; cached on first use)."""
    return hashing.fingerprint_bytes(
        endpoint.hostname.encode(), seed=0x686F7374
    ) ^ hashing.hash64(endpoint.port, seed=0x706F7274)


_uid_cache: Dict[Endpoint, int] = {}


def uid_of(endpoint: Endpoint) -> int:
    uid = _uid_cache.get(endpoint)
    if uid is None:
        uid = endpoint_uid(endpoint)
        _uid_cache[endpoint] = uid
    return uid


def ring_key(endpoint: Endpoint, k: int) -> int:
    """Sort key of ``endpoint`` on ring ``k``."""
    return hashing.hash64(uid_of(endpoint), seed=k)


def id_fingerprint(node_id: NodeId) -> int:
    """Per-identifier contribution to the configuration id."""
    return hashing.splitmix64(
        (hashing.hash64(node_id.high & MASK64, _SEED_ID_HIGH)
         + hashing.hash64(node_id.low & MASK64, _SEED_ID_LOW)) & MASK64
    )


def member_fingerprint(endpoint: Endpoint) -> int:
    """Per-member contribution to the configuration id."""
    return hashing.hash64(uid_of(endpoint), seed=_SEED_MEMBER)


def configuration_id(id_fp_sum: int, member_fp_sum: int) -> int:
    """Combine the two running sums into the 64-bit configuration id."""
    return hashing.splitmix64(
        (hashing.splitmix64(id_fp_sum & MASK64) + (member_fp_sum & MASK64)) & MASK64
    )


class NodeAlreadyInRingError(RuntimeError):
    pass


class NodeNotInRingError(RuntimeError):
    pass


class UUIDAlreadySeenError(RuntimeError):
    pass


class Configuration:
    """Snapshot sufficient to bootstrap an identical view.

    Reference: MembershipView.Configuration (MembershipView.java:526-557);
    what joiners receive (MembershipService.java:729-737) and this
    framework's checkpoint format (SURVEY.md §5 checkpoint/resume).
    """

    def __init__(self, node_ids: Sequence[NodeId], endpoints: Sequence[Endpoint],
                 id_fp_sum: Optional[int] = None,
                 member_fp_sum: Optional[int] = None):
        self.node_ids: Tuple[NodeId, ...] = tuple(node_ids)
        self.endpoints: Tuple[Endpoint, ...] = tuple(endpoints)
        # A view snapshotting itself passes its incrementally maintained
        # fingerprint sums; a Configuration deserialized from the wire
        # recomputes them lazily.
        self._id_fp_sum = id_fp_sum
        self._member_fp_sum = member_fp_sum

    def get_configuration_id(self) -> int:
        if self._id_fp_sum is None:
            self._id_fp_sum = sum(
                id_fingerprint(i) for i in self.node_ids) & MASK64
        if self._member_fp_sum is None:
            self._member_fp_sum = sum(
                member_fingerprint(e) for e in self.endpoints) & MASK64
        return configuration_id(self._id_fp_sum, self._member_fp_sum)

    def recompute_configuration_id(self) -> int:
        """Full O(N) re-hash, ignoring any cached sums — the equivalence
        check for the incremental path."""
        id_sum = sum(id_fingerprint(i) for i in self.node_ids) & MASK64
        mem_sum = sum(member_fingerprint(e) for e in self.endpoints) & MASK64
        return configuration_id(id_sum, mem_sum)


class MembershipView:
    """K rings of the membership, ordered by seeded hash."""

    def __init__(self, k: int, node_ids: Sequence[NodeId] = (),
                 endpoints: Sequence[Endpoint] = ()):
        assert k > 0
        self.K = k
        # ring[k] is a sorted list of (ring_key, uid, Endpoint)
        self._rings: List[List[Tuple[int, int, Endpoint]]] = [[] for _ in range(k)]
        self._all_nodes: Dict[Endpoint, None] = {}
        self._identifiers_seen: set[NodeId] = set()
        self._id_fp_sum = 0
        self._member_fp_sum = 0
        self._cached_observers: Dict[Endpoint, List[Endpoint]] = {}
        for node_id in node_ids:
            self._identifiers_seen.add(node_id)
            self._id_fp_sum = (self._id_fp_sum + id_fingerprint(node_id)) & MASK64
        for endpoint in endpoints:
            self._insert(endpoint)

    # -- internal helpers ---------------------------------------------------

    def _entry(self, endpoint: Endpoint, k: int) -> Tuple[int, int, Endpoint]:
        return (ring_key(endpoint, k), uid_of(endpoint), endpoint)

    def _insert(self, endpoint: Endpoint) -> None:
        for k in range(self.K):
            bisect.insort(self._rings[k], self._entry(endpoint, k))
        self._all_nodes[endpoint] = None
        self._member_fp_sum = (self._member_fp_sum + member_fingerprint(endpoint)) & MASK64

    def _remove(self, endpoint: Endpoint) -> None:
        for k in range(self.K):
            ring = self._rings[k]
            i = bisect.bisect_left(ring, self._entry(endpoint, k))
            assert i < len(ring) and ring[i][2] == endpoint
            ring.pop(i)
        del self._all_nodes[endpoint]
        self._member_fp_sum = (self._member_fp_sum - member_fingerprint(endpoint)) & MASK64

    def _neighbor(self, k: int, endpoint: Endpoint, direction: int) -> Optional[Endpoint]:
        """Successor (+1) or predecessor (-1) of ``endpoint``'s position on
        ring ``k`` (endpoint itself excluded, wrap-around)."""
        ring = self._rings[k]
        if not ring:
            return None
        entry = self._entry(endpoint, k)
        if direction > 0:
            i = bisect.bisect_right(ring, entry)
            candidate = ring[i % len(ring)]
        else:
            i = bisect.bisect_left(ring, entry)
            candidate = ring[(i - 1) % len(ring)]
        if candidate[2] == endpoint:
            return None  # only element is the endpoint itself
        return candidate[2]

    # -- queries (reference API surface) ------------------------------------

    def is_safe_to_join(self, node: Endpoint, node_id: NodeId) -> JoinStatusCode:
        """MembershipView.java:100-115."""
        if node in self._all_nodes:
            return JoinStatusCode.HOSTNAME_ALREADY_IN_RING
        if node_id in self._identifiers_seen:
            return JoinStatusCode.UUID_ALREADY_IN_RING
        return JoinStatusCode.SAFE_TO_JOIN

    def ring_add(self, node: Endpoint, node_id: NodeId) -> None:
        """MembershipView.java:123-160."""
        if node_id in self._identifiers_seen:
            raise UUIDAlreadySeenError(f"{node} identifier already seen: {node_id}")
        if node in self._all_nodes:
            raise NodeAlreadyInRingError(str(node))
        self._insert(node)
        self._identifiers_seen.add(node_id)
        self._id_fp_sum = (self._id_fp_sum + id_fingerprint(node_id)) & MASK64
        self._cached_observers.clear()

    def ring_delete(self, node: Endpoint) -> None:
        """MembershipView.java:167-201."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        self._remove(node)
        self._cached_observers.clear()

    def get_observers_of(self, node: Endpoint) -> List[Endpoint]:
        """Ring successors of a member (MembershipView.java:210-257)."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        cached = self._cached_observers.get(node)
        if cached is not None:
            return list(cached)
        if len(self._all_nodes) <= 1:
            result: List[Endpoint] = []
        else:
            result = [self._neighbor(k, node, +1) for k in range(self.K)]
        self._cached_observers[node] = result
        return list(result)

    def get_subjects_of(self, node: Endpoint) -> List[Endpoint]:
        """Ring predecessors of a member (MembershipView.java:267-282)."""
        if node not in self._all_nodes:
            raise NodeNotInRingError(str(node))
        if len(self._all_nodes) <= 1:
            return []
        return [self._neighbor(k, node, -1) for k in range(self.K)]

    def get_expected_observers_of(self, node: Endpoint) -> List[Endpoint]:
        """Gatekeepers for a joiner: predecessors of its would-be position
        (MembershipView.java:292-303 — deliberately predecessors)."""
        if not self._rings[0]:
            return []
        return [self._neighbor(k, node, -1) for k in range(self.K)]

    def get_ring_numbers(self, observer: Endpoint, subject: Endpoint) -> List[int]:
        """Indices k such that ``subject`` is ``observer``'s subject on ring k
        (MembershipView.java:397-418)."""
        subjects = self.get_subjects_of(observer)
        return [k for k, s in enumerate(subjects) if s == subject]

    def is_host_present(self, endpoint: Endpoint) -> bool:
        return endpoint in self._all_nodes

    def is_identifier_present(self, node_id: NodeId) -> bool:
        return node_id in self._identifiers_seen

    def get_ring(self, k: int) -> List[Endpoint]:
        return [e for _, _, e in self._rings[k]]

    def get_membership_size(self) -> int:
        return len(self._all_nodes)

    def get_current_configuration_id(self) -> int:
        return configuration_id(self._id_fp_sum, self._member_fp_sum)

    def get_configuration(self) -> Configuration:
        # Hand over the running sums: the snapshot's configuration id is
        # then O(1) instead of an O(N) re-hash per joiner response.
        return Configuration(
            sorted(self._identifiers_seen, key=lambda i: (i.high, i.low)),
            self.get_ring(0),
            id_fp_sum=self._id_fp_sum,
            member_fp_sum=self._member_fp_sum,
        )

    def ring0_sort_key(self, endpoint: Endpoint):
        """Consistent sort order for endpoint lists (ring-0 hash order);
        reference AddressComparator on ring 0 (MembershipView.java:470-472)."""
        return (ring_key(endpoint, 0), uid_of(endpoint))
