"""Default edge failure detector: probe-based ping-pong.

Mirrors PingPongFailureDetector.java:39-142:
- each FD interval, probe the subject (best-effort);
- failed/lost probes increment a failure count; at >= failure_threshold
  (reference: 10) the edge is reported DOWN exactly once;
- a BOOTSTRAPPING response (node in the view whose protocol has not started
  yet) is tolerated up to bootstrap_tolerance times (reference: 30) before
  counting as failures.

Probes use the network's synchronous fast path (see SimNetwork.probe); the
reference's probe deadline equals one FD interval so the timing is
equivalent, and the TPU kernel engine evaluates probes the same way.
"""
from __future__ import annotations

from typing import Callable

from rapid_tpu.oracle.interfaces import IEdgeFailureDetectorFactory
from rapid_tpu.types import Endpoint, ProbeStatus


class PingPongFailureDetector:
    def __init__(self, network, address: Endpoint, subject: Endpoint,
                 notify: Callable[[], None],
                 failure_threshold: int = 10,
                 bootstrap_tolerance: int = 30) -> None:
        self._network = network
        self._address = address
        self._subject = subject
        self._notify = notify
        self._failure_threshold = failure_threshold
        self._bootstrap_tolerance = bootstrap_tolerance
        self._failure_count = 0
        self._bootstrap_responses = 0
        self._notified = False

    def __call__(self) -> None:
        if self._failure_count >= self._failure_threshold:
            if not self._notified:
                self._notified = True
                self._notify()
            return
        response = self._network.probe(self._address, self._subject)
        if response is None:
            self._failure_count += 1
        elif response.status == ProbeStatus.BOOTSTRAPPING:
            self._bootstrap_responses += 1
            if self._bootstrap_responses > self._bootstrap_tolerance:
                self._failure_count += 1


class PingPongFailureDetectorFactory(IEdgeFailureDetectorFactory):
    def __init__(self, network, address: Endpoint,
                 failure_threshold: int = 10,
                 bootstrap_tolerance: int = 30) -> None:
        self._network = network
        self._address = address
        self._failure_threshold = failure_threshold
        self._bootstrap_tolerance = bootstrap_tolerance

    def create_instance(self, subject: Endpoint,
                        notify: Callable[[], None]) -> Callable[[], None]:
        return PingPongFailureDetector(
            self._network, self._address, subject, notify,
            self._failure_threshold, self._bootstrap_tolerance,
        )
