"""Pluggable SPIs of the framework (host side).

These mirror the reference's pluggable layers (SURVEY.md §1 L1/L2):

- ``IMessagingClient`` / ``IMessagingServer``  (messaging/IMessagingClient.java:26-48,
  messaging/IMessagingServer.java:24-40)
- ``IBroadcaster``                             (messaging/IBroadcaster.java:28-32)
- ``IEdgeFailureDetectorFactory``              (monitoring/IEdgeFailureDetectorFactory.java:32-34)
- ``IScheduler`` abstracts the reference's scheduled executor
  (SharedResources.java:55-56) into virtual-time ticks so every run is
  deterministic and the TPU engine can reproduce it bit-for-bit.

Responses are modeled as callbacks rather than futures: the simulator is
single-threaded over virtual time, which is exactly the execution model the
reference enforces with its single protocol executor (SharedResources.java:54).
"""
from __future__ import annotations

import abc
from typing import Callable, List, Optional, Sequence

from rapid_tpu.types import Endpoint, RapidRequest

ResponseCallback = Callable[[object], None]  # called with the response, or None on failure


class IMessagingClient(abc.ABC):
    """Send messages to a remote node. Reference: IMessagingClient.java:26-48."""

    @abc.abstractmethod
    def send_message(self, remote: Endpoint, request: RapidRequest,
                     on_response: Optional[ResponseCallback] = None) -> None:
        """Send with retransmission semantics."""

    @abc.abstractmethod
    def send_message_best_effort(self, remote: Endpoint, request: RapidRequest,
                                 on_response: Optional[ResponseCallback] = None) -> None:
        """Send without retries."""

    def shutdown(self) -> None:  # pragma: no cover - default no-op
        pass


class IMessagingServer(abc.ABC):
    """Receive messages. Reference: IMessagingServer.java:24-40."""

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    @abc.abstractmethod
    def set_membership_service(self, service) -> None:
        """Allows the server to start before the protocol is ready; probes get
        BOOTSTRAPPING responses until then (GrpcServer.java:53-96)."""


class IBroadcaster(abc.ABC):
    """Reference: IBroadcaster.java:28-32."""

    @abc.abstractmethod
    def broadcast(self, request: RapidRequest) -> None: ...

    @abc.abstractmethod
    def set_membership(self, recipients: Sequence[Endpoint]) -> None: ...


class UnicastToAllBroadcaster(IBroadcaster):
    """Default broadcaster: best-effort unicast to every member
    (UnicastToAllBroadcaster.java:36-62; recipient order shuffled per
    configuration)."""

    def __init__(self, client: IMessagingClient, rng=None) -> None:
        self._client = client
        self._rng = rng
        self._recipients: List[Endpoint] = []

    def set_membership(self, recipients: Sequence[Endpoint]) -> None:
        self._recipients = list(recipients)
        if self._rng is not None:
            self._rng.shuffle(self._recipients)

    def broadcast(self, request: RapidRequest) -> None:
        for recipient in self._recipients:
            self._client.send_message_best_effort(recipient, request)


class IScheduler(abc.ABC):
    """Virtual-time task scheduling in ticks."""

    @abc.abstractmethod
    def schedule(self, delay_ticks: int, fn: Callable[[], None]) -> object:
        """Run ``fn`` after ``delay_ticks``; returns a cancellation handle."""

    @abc.abstractmethod
    def cancel(self, handle: object) -> None: ...

    @abc.abstractmethod
    def now(self) -> int:
        """Current tick."""


class IEdgeFailureDetectorFactory(abc.ABC):
    """Per-edge failure detector SPI.

    ``create_instance(subject, notify)`` returns a zero-arg callable run once
    per failure-detector interval; implementations call ``notify()`` to mark
    the observer->subject edge faulty.
    Reference: IEdgeFailureDetectorFactory.java:32-34.
    """

    @abc.abstractmethod
    def create_instance(self, subject: Endpoint,
                        notify: Callable[[], None]) -> Callable[[], None]: ...
