"""The membership protocol state machine (host oracle).

Mirrors MembershipService.java:73-754 on virtual time:

- single entry point ``handle_message(request, reply)`` (reference :178-200);
- join phase 1 at a seed (:207-228) and phase 2 at gatekeepers (:236-293)
  with parked replies released only after consensus (:723-748);
- batched alerts -> validity filter -> cut detector -> proposal ->
  FastPaxos (:304-358), with the announced-proposal latch (:322);
- decideViewChange applies the cut: ring add/delete, metadata update, event
  subscriptions, KICKED detection, fresh FastPaxos + cut detector state, FD
  re-subscription (:389-448);
- alert batching with a one-window quiescence flush (:617-641);
- edge-failure notifications from the pluggable FD (:476-499), leave
  handling (:376-381), probes (:453-456).

Timers are ticks on the shared deterministic scheduler; one tick equals the
reference's 100 ms batching window (see Settings).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from rapid_tpu.events import ClusterEvents, ClusterStatusChange, NodeStatusChange
from rapid_tpu.oracle.cut_detector import MultiNodeCutDetector
from rapid_tpu.oracle.interfaces import (
    IBroadcaster,
    IEdgeFailureDetectorFactory,
    IMessagingClient,
    IScheduler,
    UnicastToAllBroadcaster,
)
from rapid_tpu.oracle.membership_view import MembershipView
from rapid_tpu.oracle.metadata import MetadataManager
from rapid_tpu.oracle.paxos import FastPaxos
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    CONSENSUS_MESSAGE_TYPES,
    EdgeStatus,
    Endpoint,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    Metadata,
    NodeId,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    ProbeStatus,
    Response,
)


class MissingJoinerIdError(RuntimeError):
    """A decided proposal contains a joiner whose UP alert (carrying its
    NodeId) this node never received. The reference crashes here too
    (`assert joinerUuid.containsKey(node)`, MembershipService.java:409);
    the simulation surfaces it as a node failure."""


class MembershipService:
    def __init__(self, my_addr: Endpoint, cut_detector: MultiNodeCutDetector,
                 view: MembershipView, settings: Settings,
                 client: IMessagingClient, scheduler: IScheduler,
                 fd_factory: IEdgeFailureDetectorFactory,
                 metadata_map: Optional[Dict[Endpoint, Metadata]] = None,
                 subscriptions: Optional[Dict[ClusterEvents, List[Callable]]] = None,
                 broadcaster: Optional[IBroadcaster] = None,
                 rng=None) -> None:
        self.my_addr = my_addr
        self.settings = settings
        self.view = view
        self.cut_detector = cut_detector
        self.client = client
        self.scheduler = scheduler
        self.fd_factory = fd_factory
        self.rng = rng
        self.metadata_manager = MetadataManager()
        if metadata_map:
            self.metadata_manager.add_metadata(metadata_map)
        # No recipient shuffle (the reference shuffles only to spread network
        # load, UnicastToAllBroadcaster.java:56-62; per-receiver semantics are
        # unaffected and an unshuffled order keeps runs reproducible).
        self.broadcaster = broadcaster or UnicastToAllBroadcaster(client, None)
        self.subscriptions: Dict[ClusterEvents, List[Callable]] = {
            e: [] for e in ClusterEvents
        }
        if subscriptions:
            for event, callbacks in subscriptions.items():
                self.subscriptions[event].extend(callbacks)

        # joiners parked awaiting consensus: endpoint -> [reply callbacks]
        self._joiners_to_respond_to: Dict[Endpoint, List[Callable]] = {}
        self._joiner_uuid: Dict[Endpoint, NodeId] = {}
        self._joiner_metadata: Dict[Endpoint, Metadata] = {}

        # alert batching
        self._send_queue: List[AlertMessage] = []
        self._last_enqueue_tick = -1

        self._announced_proposal = False
        self._stopped = False
        self._fd_jobs: List[object] = []
        self._fd_instances: List[Callable[[], None]] = []

        self.broadcaster.set_membership(self.view.get_ring(0))
        self.fast_paxos = self._new_fast_paxos()
        self._create_failure_detectors()
        self._batcher_job = self._schedule_periodic(
            settings.batching_window_ticks, self._alert_batcher_tick
        )

        # Initial VIEW_CHANGE callbacks: start/join completed (ref :162-168).
        initial = ClusterStatusChange(
            self.view.get_current_configuration_id(),
            tuple(self.view.get_ring(0)),
            tuple(NodeStatusChange(n, EdgeStatus.UP,
                                   tuple(self.metadata_manager.get(n).items()))
                  for n in self.view.get_ring(0)),
        )
        self._fire(ClusterEvents.VIEW_CHANGE, initial)

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------

    def _schedule_periodic(self, interval: int, fn: Callable[[], None]) -> dict:
        """Periodic task aligned to global tick multiples of ``interval``, so
        every node's FD/batcher fires on the same ticks — the same global
        rounds the TPU engine uses."""
        job = {"cancelled": False}

        def run():
            if job["cancelled"] or self._stopped:
                return
            fn()
            self.scheduler.schedule(interval, run)

        now = self.scheduler.now()
        self.scheduler.schedule(interval - (now % interval), run)
        return job

    def _fire(self, event: ClusterEvents, change: ClusterStatusChange) -> None:
        for callback in self.subscriptions[event]:
            callback(change)

    def _new_fast_paxos(self) -> FastPaxos:
        return FastPaxos(
            self.my_addr,
            self.view.get_current_configuration_id(),
            self.view.get_membership_size(),
            self.client,
            self.broadcaster,
            self.scheduler,
            self._decide_view_change,
            fallback_base_delay_ticks=self.settings.fallback_base_delay_ticks,
            tick_ms=self.settings.tick_ms,
            rng=self.rng,
        )

    # ------------------------------------------------------------------
    # message entry point
    # ------------------------------------------------------------------

    def handle_message(self, msg, reply: Callable[[object], None]) -> None:
        if self._stopped:
            return
        if isinstance(msg, PreJoinMessage):
            self._handle_pre_join(msg, reply)
        elif isinstance(msg, JoinMessage):
            self._handle_join_phase2(msg, reply)
        elif isinstance(msg, BatchedAlertMessage):
            self._handle_batched_alerts(msg)
            reply(Response())
        elif isinstance(msg, CONSENSUS_MESSAGE_TYPES):
            self.fast_paxos.handle_messages(msg)
            reply(Response())
        elif isinstance(msg, LeaveMessage):
            self._edge_failure_notification(
                msg.sender, self.view.get_current_configuration_id()
            )
            reply(Response())
        elif isinstance(msg, ProbeMessage):
            reply(ProbeResponse(ProbeStatus.OK))
        else:
            raise TypeError(f"Unidentified request type {type(msg)}")

    # ------------------------------------------------------------------
    # join protocol (server side)
    # ------------------------------------------------------------------

    def _handle_pre_join(self, msg: PreJoinMessage, reply) -> None:
        """Phase 1 at the seed (MembershipService.java:207-228)."""
        status = self.view.is_safe_to_join(msg.sender, msg.node_id)
        endpoints: Tuple[Endpoint, ...] = ()
        if status in (JoinStatusCode.SAFE_TO_JOIN,
                      JoinStatusCode.HOSTNAME_ALREADY_IN_RING):
            endpoints = tuple(self.view.get_expected_observers_of(msg.sender))
        reply(JoinResponse(
            sender=self.my_addr,
            status_code=status,
            configuration_id=self.view.get_current_configuration_id(),
            endpoints=endpoints,
        ))

    def _handle_join_phase2(self, msg: JoinMessage, reply) -> None:
        """Phase 2 at a gatekeeper (MembershipService.java:236-293)."""
        current_configuration = self.view.get_current_configuration_id()
        if current_configuration == msg.configuration_id:
            # Park the reply; enqueue an UP alert carrying the joiner identity.
            self._joiners_to_respond_to.setdefault(msg.sender, []).append(reply)
            self._enqueue_alert(AlertMessage(
                edge_src=self.my_addr,
                edge_dst=msg.sender,
                edge_status=EdgeStatus.UP,
                configuration_id=current_configuration,
                ring_numbers=msg.ring_numbers,
                node_id=msg.node_id,
                metadata=msg.metadata,
            ))
            return
        # Configuration changed between phases 1 and 2.
        configuration = self.view.get_configuration()
        if self.view.is_host_present(msg.sender) and \
                self.view.is_identifier_present(msg.node_id):
            # The cluster already added the joiner: stream it the config.
            all_md = self.metadata_manager.get_all_metadata()
            reply(JoinResponse(
                sender=self.my_addr,
                status_code=JoinStatusCode.SAFE_TO_JOIN,
                configuration_id=configuration.get_configuration_id(),
                endpoints=configuration.endpoints,
                identifiers=configuration.node_ids,
                metadata=tuple((k, tuple(v.items())) for k, v in all_md.items()),
            ))
        else:
            reply(JoinResponse(
                sender=self.my_addr,
                status_code=JoinStatusCode.CONFIG_CHANGED,
                configuration_id=configuration.get_configuration_id(),
            ))

    # ------------------------------------------------------------------
    # alerts -> cut detection -> consensus
    # ------------------------------------------------------------------

    def _filter_alert(self, alert: AlertMessage, config_id: int) -> bool:
        """Validity filter (MembershipService.java:648-679)."""
        if alert.configuration_id != config_id:
            return False
        present = self.view.is_host_present(alert.edge_dst)
        if alert.edge_status == EdgeStatus.UP and present:
            return False
        if alert.edge_status == EdgeStatus.DOWN and not present:
            return False
        return True

    def _handle_batched_alerts(self, batch: BatchedAlertMessage) -> None:
        """MembershipService.java:304-358."""
        if self._announced_proposal:
            return
        config_id = self.view.get_current_configuration_id()
        proposal: Dict[Endpoint, None] = {}
        for alert in batch.messages:
            if not self._filter_alert(alert, config_id):
                continue
            if alert.edge_status == EdgeStatus.UP:
                # Stash joiner identity for the eventual ring add (ref :681-689).
                self._joiner_uuid[alert.edge_dst] = alert.node_id
                self._joiner_metadata[alert.edge_dst] = dict(alert.metadata)
            for node in self.cut_detector.aggregate_for_proposal(alert):
                proposal[node] = None
        for node in self.cut_detector.invalidate_failing_edges(self.view):
            proposal[node] = None

        if proposal:
            self._announced_proposal = True
            change = ClusterStatusChange(
                config_id, tuple(self.view.get_ring(0)),
                tuple(self._status_change(n) for n in proposal),
            )
            self._fire(ClusterEvents.VIEW_CHANGE_PROPOSAL, change)
            ordered = sorted(proposal, key=self.view.ring0_sort_key)
            self.fast_paxos.propose(ordered)

    def _status_change(self, node: Endpoint) -> NodeStatusChange:
        status = EdgeStatus.DOWN if self.view.is_host_present(node) else EdgeStatus.UP
        return NodeStatusChange(node, status,
                                tuple(self.metadata_manager.get(node).items()))

    # ------------------------------------------------------------------
    # view change application
    # ------------------------------------------------------------------

    def _decide_view_change(self, proposal: List[Endpoint]) -> None:
        """MembershipService.java:389-448."""
        self._cancel_failure_detectors()

        status_changes = []
        for node in proposal:
            if self.view.is_host_present(node):
                self.view.ring_delete(node)
                status_changes.append(NodeStatusChange(
                    node, EdgeStatus.DOWN,
                    tuple(self.metadata_manager.get(node).items())))
                self.metadata_manager.remove_node(node)
            else:
                if node not in self._joiner_uuid:
                    raise MissingJoinerIdError(
                        f"{self.my_addr} decided on joiner {node} without its id")
                node_id = self._joiner_uuid.pop(node)
                self.view.ring_add(node, node_id)
                metadata = self._joiner_metadata.pop(node, {})
                if metadata:
                    self.metadata_manager.add_metadata({node: metadata})
                status_changes.append(NodeStatusChange(
                    node, EdgeStatus.UP, tuple(metadata.items())))

        configuration_id = self.view.get_current_configuration_id()
        change = ClusterStatusChange(
            configuration_id, tuple(self.view.get_ring(0)), tuple(status_changes)
        )
        self._fire(ClusterEvents.VIEW_CHANGE, change)

        # Reset for the next round.
        self.cut_detector.clear()
        self._announced_proposal = False
        self.fast_paxos = self._new_fast_paxos()
        self.broadcaster.set_membership(self.view.get_ring(0))

        if self.view.is_host_present(self.my_addr):
            self._create_failure_detectors()
        else:
            self._fire(ClusterEvents.KICKED, change)
            self.stop()

        self._respond_to_joiners(proposal)

    def _respond_to_joiners(self, proposal: List[Endpoint]) -> None:
        """MembershipService.java:723-748."""
        configuration = self.view.get_configuration()
        all_md = self.metadata_manager.get_all_metadata()
        response = JoinResponse(
            sender=self.my_addr,
            status_code=JoinStatusCode.SAFE_TO_JOIN,
            configuration_id=configuration.get_configuration_id(),
            endpoints=configuration.endpoints,
            identifiers=configuration.node_ids,
            metadata=tuple((k, tuple(v.items())) for k, v in all_md.items()),
        )
        for node in proposal:
            for reply in self._joiners_to_respond_to.pop(node, []):
                reply(response)

    # ------------------------------------------------------------------
    # failure detection + alert batching
    # ------------------------------------------------------------------

    def _edge_failure_notification(self, subject: Endpoint, configuration_id: int) -> None:
        """MembershipService.java:476-499."""
        if configuration_id != self.view.get_current_configuration_id():
            return
        self._enqueue_alert(AlertMessage(
            edge_src=self.my_addr,
            edge_dst=subject,
            edge_status=EdgeStatus.DOWN,
            configuration_id=configuration_id,
            ring_numbers=tuple(self.view.get_ring_numbers(self.my_addr, subject)),
        ))

    def _enqueue_alert(self, msg: AlertMessage) -> None:
        self._last_enqueue_tick = self.scheduler.now()
        self._send_queue.append(msg)

    def _alert_batcher_tick(self) -> None:
        """Flush once the queue has been quiescent for one batching window
        (MembershipService.java:617-641)."""
        if not self._send_queue or self._last_enqueue_tick < 0:
            return
        if self.scheduler.now() - self._last_enqueue_tick \
                < self.settings.batching_window_ticks:
            return
        messages = tuple(self._send_queue)
        self._send_queue.clear()
        self.broadcaster.broadcast(BatchedAlertMessage(self.my_addr, messages))

    def _create_failure_detectors(self) -> None:
        """One FD per unique subject (MembershipService.java:701-711; the
        reference schedules one job per ring entry — duplicates of the same
        subject behave identically, so they are deduplicated here)."""
        config_id = self.view.get_current_configuration_id()
        subjects = list(dict.fromkeys(self.view.get_subjects_of(self.my_addr)))
        for subject in subjects:
            notify = (lambda s=subject, c=config_id:
                      self._edge_failure_notification(s, c))
            instance = self.fd_factory.create_instance(subject, notify)
            self._fd_instances.append(instance)
            job = self._schedule_periodic_fd(instance)
            self._fd_jobs.append(job)

    def _schedule_periodic_fd(self, instance: Callable[[], None]) -> dict:
        return self._schedule_periodic(self.settings.fd_interval_ticks, instance)

    def _cancel_failure_detectors(self) -> None:
        for job in self._fd_jobs:
            job["cancelled"] = True
        self._fd_jobs.clear()
        self._fd_instances.clear()

    # ------------------------------------------------------------------
    # public API surface (used by the Cluster facade)
    # ------------------------------------------------------------------

    def get_membership_view(self) -> List[Endpoint]:
        return self.view.get_ring(0)

    def get_membership_size(self) -> int:
        return self.view.get_membership_size()

    def get_configuration_id(self) -> int:
        return self.view.get_current_configuration_id()

    def get_metadata(self) -> Dict[Endpoint, Metadata]:
        return self.metadata_manager.get_all_metadata()

    def register_subscription(self, event: ClusterEvents,
                              callback: Callable[[ClusterStatusChange], None]) -> None:
        self.subscriptions[event].append(callback)

    def leave(self) -> None:
        """Proactively trigger DOWN alerts at our observers
        (MembershipService.java:549-569)."""
        try:
            observers = self.view.get_observers_of(self.my_addr)
        except Exception:
            return  # already removed
        for observer in observers:
            self.client.send_message_best_effort(
                observer, LeaveMessage(self.my_addr))

    def stop(self) -> None:
        self._stopped = True
        self._cancel_failure_detectors()
        self._batcher_job["cancelled"] = True
