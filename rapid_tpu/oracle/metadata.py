"""Per-node metadata tags.

Reference: MetadataManager.java:31-70 — immutable per-node key->bytes maps,
add-if-absent semantics, removed when a node leaves, full map shared with
joiners.
"""
from __future__ import annotations

from typing import Dict, Mapping

from rapid_tpu.types import Endpoint, Metadata


class MetadataManager:
    def __init__(self) -> None:
        self._table: Dict[Endpoint, Metadata] = {}

    def get(self, node: Endpoint) -> Metadata:
        return dict(self._table.get(node, {}))

    def add_metadata(self, roles: Mapping[Endpoint, Metadata]) -> None:
        """Add-if-absent, per the reference (MetadataManager.java:46-52)."""
        for node, metadata in roles.items():
            self._table.setdefault(node, dict(metadata))

    def remove_node(self, node: Endpoint) -> None:
        self._table.pop(node, None)

    def get_all_metadata(self) -> Dict[Endpoint, Metadata]:
        return {node: dict(md) for node, md in self._table.items()}
