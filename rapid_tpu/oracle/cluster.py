"""Cluster facade: bootstrapping, joining, leaving simulated nodes.

Mirrors the reference public API (Cluster.java:70-507):

- ``Cluster.start()`` bootstraps a one-node cluster (ref :259-284);
- ``Cluster.join(seed)`` runs the two-phase bootstrap with up to 5 retries,
  refreshing the NodeId on UUID_ALREADY_IN_RING and treating
  HOSTNAME_ALREADY_IN_RING as "stream me the configuration" via a sentinel
  config id of -1 (ref :307-441);
- ``get_memberlist / get_membership_size / get_cluster_metadata /
  register_subscription / leave_gracefully / shutdown`` (ref :98-164).

The reference's join blocks a thread; on virtual time it is a state machine
advanced by ticks: start a join, run the simulation, and observe
``cluster.is_active`` / ``ClusterEvents.VIEW_CHANGE``.
"""
from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional

from rapid_tpu.events import ClusterEvents, ClusterStatusChange
from rapid_tpu.oracle.cut_detector import MultiNodeCutDetector
from rapid_tpu.oracle.failure_detector import PingPongFailureDetectorFactory
from rapid_tpu.oracle.interfaces import IEdgeFailureDetectorFactory
from rapid_tpu.oracle.membership_view import MembershipView
from rapid_tpu.oracle.service import MembershipService
from rapid_tpu.oracle.simulation import SimMessagingClient, SimNetwork, SimServer
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    Metadata,
    NodeId,
    PreJoinMessage,
)


class JoinError(RuntimeError):
    pass


def default_rng(settings: Settings, listen_address: Endpoint) -> random.Random:
    """The rng a ``Cluster`` built without an explicit one draws NodeIds
    from. Exposed so host-side planners (``rapid_tpu.engine.churn``) can
    replicate a joiner's identifier sequence without creating the node."""
    return random.Random(hash((settings.seed, str(listen_address))) & 0xFFFFFFFF)


class Cluster:
    """One simulated cluster member."""

    def __init__(self, network: SimNetwork, listen_address: Endpoint,
                 settings: Optional[Settings] = None,
                 metadata: Optional[Metadata] = None,
                 fd_factory: Optional[IEdgeFailureDetectorFactory] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.network = network
        self.listen_address = listen_address
        self.settings = settings or network.settings
        self.metadata = dict(metadata or {})
        self.rng = rng or default_rng(self.settings, listen_address)
        self.server = SimServer(network, listen_address)
        self.client = SimMessagingClient(network, listen_address)
        self.fd_factory = fd_factory or PingPongFailureDetectorFactory(
            network, listen_address,
            self.settings.fd_failure_threshold,
            self.settings.fd_bootstrap_tolerance,
        )
        self.membership_service: Optional[MembershipService] = None
        self._subscriptions: Dict[ClusterEvents, List[Callable]] = {
            e: [] for e in ClusterEvents
        }
        self._join_state: Optional[dict] = None
        self.join_failed = False

    # -- builder-ish configuration ------------------------------------------

    def register_subscription(self, event: ClusterEvents,
                              callback: Callable[[ClusterStatusChange], None]) -> None:
        if self.membership_service is not None:
            self.membership_service.register_subscription(event, callback)
        else:
            self._subscriptions[event].append(callback)

    # -- bootstrap -----------------------------------------------------------

    @property
    def is_active(self) -> bool:
        return self.membership_service is not None

    def _fresh_node_id(self) -> NodeId:
        return NodeId(self.rng.getrandbits(64), self.rng.getrandbits(64))

    def start(self) -> "Cluster":
        """Bootstrap a one-node cluster (the seed). Cluster.java:259-284."""
        node_id = self._fresh_node_id()
        view = MembershipView(self.settings.K, [node_id], [self.listen_address])
        self._wire_service(view, {self.listen_address: self.metadata}
                           if self.metadata else {})
        return self

    def join(self, seed_address: Endpoint) -> "Cluster":
        """Begin the two-phase join; completes asynchronously over ticks
        (Cluster.java:307-348)."""
        self.server.start()
        self._join_state = {
            "seed": seed_address,
            "attempt": 0,
            "node_id": self._fresh_node_id(),
            "done": False,
        }
        self._join_attempt()
        return self

    def _join_attempt(self) -> None:
        state = self._join_state
        assert state is not None
        if state["done"]:
            return
        if state["attempt"] >= self.settings.join_attempts:
            self.join_failed = True
            self.server.shutdown()
            return
        state["attempt"] += 1
        attempt_no = state["attempt"]

        # Per-attempt timeout drives the retry loop (the reference blocks on
        # futures with a join timeout; Settings join timeout 5000 ms).
        def on_timeout():
            if not state["done"] and state["attempt"] == attempt_no:
                self._join_attempt()

        self.network.scheduler.schedule(self.settings.join_timeout_ticks, on_timeout)

        pre_join = PreJoinMessage(self.listen_address, state["node_id"])
        self.client.send_message(
            state["seed"], pre_join,
            lambda resp: self._on_phase1_response(resp, attempt_no))

    def _on_phase1_response(self, resp, attempt_no: int) -> None:
        state = self._join_state
        if state is None or state["done"] or state["attempt"] != attempt_no:
            return
        if not isinstance(resp, JoinResponse):
            return  # lost/timeout; the attempt timer retries
        if resp.status_code not in (JoinStatusCode.SAFE_TO_JOIN,
                                    JoinStatusCode.HOSTNAME_ALREADY_IN_RING):
            # Error responses that warrant a retry (Cluster.java:322-342).
            if resp.status_code == JoinStatusCode.UUID_ALREADY_IN_RING:
                state["node_id"] = self._fresh_node_id()
            self._join_attempt()
            return
        # HOSTNAME_ALREADY_IN_RING -> join with config id -1 so gatekeepers
        # stream us the configuration (Cluster.java:378-385).
        config_to_join = (
            -1 if resp.status_code == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
            else resp.configuration_id
        )
        # Group ring numbers per gatekeeper (Cluster.java:416-423).
        ring_numbers_per_observer: Dict[Endpoint, List[int]] = {}
        for ring_number, observer in enumerate(resp.endpoints):
            ring_numbers_per_observer.setdefault(observer, []).append(ring_number)
        for observer, ring_numbers in ring_numbers_per_observer.items():
            msg = JoinMessage(
                sender=self.listen_address,
                node_id=state["node_id"],
                configuration_id=config_to_join,
                ring_numbers=tuple(ring_numbers),
                metadata=tuple(self.metadata.items()),
            )
            self.client.send_message(
                observer, msg,
                lambda r: self._on_phase2_response(r, config_to_join, attempt_no))

    def _on_phase2_response(self, resp, config_to_join: int, attempt_no: int) -> None:
        state = self._join_state
        if state is None or state["done"]:
            return
        if not isinstance(resp, JoinResponse):
            return
        if resp.status_code != JoinStatusCode.SAFE_TO_JOIN:
            return
        if resp.configuration_id == config_to_join:
            return
        state["done"] = True
        # Build the view from the streamed configuration (Cluster.java:446-478).
        view = MembershipView(self.settings.K, resp.identifiers, resp.endpoints)
        metadata_map = {node: dict(md) for node, md in resp.metadata}
        self._wire_service(view, metadata_map)

    def _wire_service(self, view: MembershipView,
                      metadata_map: Dict[Endpoint, Metadata]) -> None:
        cut_detector = MultiNodeCutDetector(
            self.settings.K, self.settings.H, self.settings.L)
        self.membership_service = MembershipService(
            self.listen_address, cut_detector, view, self.settings,
            self.client, self.network.scheduler, self.fd_factory,
            metadata_map, self._subscriptions, rng=self.rng,
        )
        self.server.set_membership_service(self.membership_service)
        self.server.start()

    # -- observability (Cluster.java:98-164) ---------------------------------

    def get_memberlist(self) -> List[Endpoint]:
        self._check_active()
        return self.membership_service.get_membership_view()

    def get_membership_size(self) -> int:
        self._check_active()
        return self.membership_service.get_membership_size()

    def get_configuration_id(self) -> int:
        self._check_active()
        return self.membership_service.get_configuration_id()

    def get_cluster_metadata(self) -> Dict[Endpoint, Metadata]:
        self._check_active()
        return self.membership_service.get_metadata()

    def _check_active(self) -> None:
        if self.membership_service is None:
            raise RuntimeError(f"{self.listen_address}: cluster not active")

    # -- teardown ------------------------------------------------------------

    def leave_gracefully(self) -> None:
        """Inform observers, then shut down after the leave timeout
        (Cluster.java:145-160)."""
        self._check_active()
        self.membership_service.leave()
        self.network.scheduler.schedule(
            self.settings.leave_timeout_ticks, self.shutdown)

    def shutdown(self) -> None:
        if self.membership_service is not None:
            self.membership_service.stop()
        self.server.shutdown()
