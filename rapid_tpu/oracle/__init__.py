"""Exact-semantics host oracle of the Rapid protocol.

This package is a tick-driven, deterministic reimplementation of the
reference's protocol core (SURVEY.md §2.2): MembershipView, the multi-node
cut detector, FastPaxos + classic Paxos, and the MembershipService state
machine, plus a deterministic in-process messaging substrate. It serves as

1. ground truth for differential testing of the batched TPU kernel engine
   (``rapid_tpu.engine``), and
2. the small-N product: real multi-node clusters simulated in one process,
   the same leverage the reference gets from its in-process-transport
   ClusterTest (SURVEY.md §4.4).
"""

from rapid_tpu.oracle.membership_view import (
    MembershipView,
    Configuration,
    NodeAlreadyInRingError,
    NodeNotInRingError,
    UUIDAlreadySeenError,
)
from rapid_tpu.oracle.cut_detector import MultiNodeCutDetector
from rapid_tpu.oracle.paxos import Paxos, FastPaxos
from rapid_tpu.oracle.metadata import MetadataManager

__all__ = [
    "MembershipView",
    "Configuration",
    "MultiNodeCutDetector",
    "Paxos",
    "FastPaxos",
    "MetadataManager",
    "NodeAlreadyInRingError",
    "NodeNotInRingError",
    "UUIDAlreadySeenError",
]
