"""Multi-node cut detector (almost-everywhere agreement filter).

Mirrors MultiNodeCutDetector.java:38-179 exactly:

- Per destination node, reports are deduplicated per ring number (:93-101).
- A destination crossing L distinct-ring reports becomes "in flux"
  (updates-in-progress += 1, pre-proposal set) (:104-107).
- Crossing H moves it from pre-proposal to the pending proposal and
  decrements updates-in-progress (:109-114).
- The accumulated proposal is emitted exactly when a node crosses H while no
  node sits strictly between L and H reports (updates_in_progress == 0)
  (:116-123). Reports are *not* cleared on emission — only the pending
  proposal set is.
- ``invalidate_failing_edges`` (:137-164): for every in-flux node, edges from
  gatekeepers that are themselves in (pre-)proposal are implicitly reported
  (DOWN if the node is a member, UP if it is joining), which un-sticks mixed
  join+failure scenarios. The reference iterates its pre-proposal HashSet in
  unspecified order; this implementation fixes a deterministic order
  (insertion order) — any refinement of the reference's nondeterminism is a
  valid execution, and the kernel engine matches this one bit-for-bit.
- ``clear`` resets everything after a view change (:169-178).
"""
from __future__ import annotations

from typing import Dict, List, TYPE_CHECKING

from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint

if TYPE_CHECKING:
    from rapid_tpu.oracle.membership_view import MembershipView

_K_MIN = 3


class MultiNodeCutDetector:
    def __init__(self, k: int, h: int, l: int) -> None:
        if h > k or l > h or k < _K_MIN or l <= 0 or h <= 0:
            raise ValueError(
                f"Arguments do not satisfy K > H >= L >= 0: (K: {k}, H: {h}, L: {l})"
            )
        self.K = k
        self.H = h
        self.L = l
        self._proposal_count = 0
        self._updates_in_progress = 0
        # dst -> {ring_number -> reporter}
        self._reports_per_host: Dict[Endpoint, Dict[int, Endpoint]] = {}
        self._proposal: Dict[Endpoint, None] = {}      # insertion-ordered set
        self._pre_proposal: Dict[Endpoint, None] = {}  # insertion-ordered set
        self._seen_link_down_events = False

    def get_num_proposals(self) -> int:
        return self._proposal_count

    def aggregate_for_proposal(self, msg: AlertMessage) -> List[Endpoint]:
        result: List[Endpoint] = []
        for ring_number in msg.ring_numbers:
            result.extend(
                self._aggregate(msg.edge_src, msg.edge_dst, msg.edge_status, ring_number)
            )
        return result

    def _aggregate(self, link_src: Endpoint, link_dst: Endpoint,
                   edge_status: EdgeStatus, ring_number: int) -> List[Endpoint]:
        assert ring_number <= self.K
        if edge_status == EdgeStatus.DOWN:
            self._seen_link_down_events = True

        reports_for_host = self._reports_per_host.setdefault(link_dst, {})
        if ring_number in reports_for_host:
            return []  # duplicate announcement, ignore
        reports_for_host[ring_number] = link_src
        num_reports = len(reports_for_host)

        if num_reports == self.L:
            self._updates_in_progress += 1
            self._pre_proposal[link_dst] = None

        if num_reports == self.H:
            self._pre_proposal.pop(link_dst, None)
            self._proposal[link_dst] = None
            self._updates_in_progress -= 1
            if self._updates_in_progress == 0:
                self._proposal_count += 1
                ret = list(self._proposal)
                self._proposal.clear()
                return ret

        return []

    def invalidate_failing_edges(self, view: "MembershipView") -> List[Endpoint]:
        if not self._seen_link_down_events:
            return []

        proposals_to_return: List[Endpoint] = []
        for node_in_flux in list(self._pre_proposal):
            is_present = view.is_host_present(node_in_flux)
            observers = (
                view.get_observers_of(node_in_flux)
                if is_present
                else view.get_expected_observers_of(node_in_flux)
            )
            for ring_number, observer in enumerate(observers):
                if observer in self._proposal or observer in self._pre_proposal:
                    status = EdgeStatus.DOWN if is_present else EdgeStatus.UP
                    proposals_to_return.extend(
                        self._aggregate(observer, node_in_flux, status, ring_number)
                    )
        return proposals_to_return

    def clear(self) -> None:
        self._reports_per_host.clear()
        self._proposal.clear()
        self._updates_in_progress = 0
        self._proposal_count = 0
        self._pre_proposal.clear()
        self._seen_link_down_events = False
