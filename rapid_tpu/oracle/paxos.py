"""Consensus: Fast Paxos fast round + classic Paxos fallback (host oracle).

``Paxos`` mirrors Paxos.java:55-339 — classic single-decree Paxos with the
Fast Paxos coordinator value-selection rule (Lamport tr-2005-112, Fig. 2).
``FastPaxos`` mirrors FastPaxos.java:44-208 — the one-step fast round with
vote counting at quorum N - floor((N-1)/4), plus scheduling of the classic
fallback round after a base delay + expovariate jitter with rate 1/N.

A round is identified by a Rank (round, node_index); the fast round is always
rank (1, 1), and classic rounds start at round 2 with node_index a per-node
integer, so every classic rank orders above the fast round
(Paxos.java:246-260).
"""
from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from rapid_tpu import hashing
from rapid_tpu.oracle.interfaces import IBroadcaster, IMessagingClient, IScheduler
from rapid_tpu.oracle.membership_view import uid_of
from rapid_tpu.types import (
    Endpoint,
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    Rank,
)

Proposal = Tuple[Endpoint, ...]


def classic_rank_node_index(endpoint: Endpoint) -> int:
    """Per-node integer used as the node_index of classic-round ranks.

    The reference uses Java's Endpoint.hashCode() (Paxos.java:102); any fixed
    per-node integer gives the required total order between ranks. We use the
    low 31 bits of the node's 64-bit identity hash.
    """
    return int(hashing.hash64(uid_of(endpoint), seed=0x72616E6B) & 0x7FFFFFFF)


class Paxos:
    """Classic Paxos acceptor+coordinator state for one consensus instance."""

    def __init__(self, my_addr: Endpoint, configuration_id: int, n: int,
                 client: IMessagingClient, broadcaster: IBroadcaster,
                 on_decide: Callable[[List[Endpoint]], None]) -> None:
        self._my_addr = my_addr
        self._configuration_id = configuration_id
        self._n = n
        self._client = client
        self._broadcaster = broadcaster
        self._on_decide = on_decide

        self._rnd = Rank(0, 0)
        self._vrnd = Rank(0, 0)
        self._vval: Proposal = ()
        self._crnd = Rank(0, 0)
        self._cval: Proposal = ()
        # sender -> message (insertion-ordered; deduped per acceptor so a
        # retransmission cannot be double-counted toward the majority)
        self._phase1b_messages: Dict[Endpoint, Phase1bMessage] = {}
        # rank -> {sender -> message}
        self._accept_responses: Dict[Rank, Dict[Endpoint, Phase2bMessage]] = {}
        self._decided = False

    # -- coordinator --------------------------------------------------------

    def start_phase1a(self, round_: int) -> None:
        """Paxos.java:98-111."""
        if self._crnd.round > round_:
            return
        self._crnd = Rank(round_, classic_rank_node_index(self._my_addr))
        self._broadcaster.broadcast(
            Phase1aMessage(self._my_addr, self._configuration_id, self._crnd)
        )

    def handle_phase1a(self, msg: Phase1aMessage) -> None:
        """Acceptor: promise if the rank is new. Paxos.java:118-148."""
        if msg.configuration_id != self._configuration_id:
            return
        if self._rnd < msg.rank:
            self._rnd = msg.rank
        else:
            return
        self._client.send_message(
            msg.sender,
            Phase1bMessage(self._my_addr, self._configuration_id,
                           rnd=self._rnd, vrnd=self._vrnd, vval=self._vval),
        )

    def handle_phase1b(self, msg: Phase1bMessage) -> None:
        """Coordinator: gather promises; past majority, select a value with
        the coordinator rule and broadcast phase2a. Paxos.java:156-188."""
        if msg.configuration_id != self._configuration_id:
            return
        if self._crnd != msg.rnd:
            return
        self._phase1b_messages[msg.sender] = msg
        if len(self._phase1b_messages) > self._n // 2:
            chosen = self.select_proposal_using_coordinator_rule(
                list(self._phase1b_messages.values())
            )
            if not self._cval and chosen:
                self._cval = chosen
                self._broadcaster.broadcast(
                    Phase2aMessage(self._my_addr, self._configuration_id,
                                   rnd=self._crnd, vval=chosen)
                )

    # -- acceptor -----------------------------------------------------------

    def handle_phase2a(self, msg: Phase2aMessage) -> None:
        """Accept and broadcast the vote to everyone. Paxos.java:195-216."""
        if msg.configuration_id != self._configuration_id:
            return
        if self._rnd <= msg.rnd and self._vrnd != msg.rnd:
            self._rnd = msg.rnd
            self._vrnd = msg.rnd
            self._vval = tuple(msg.vval)
            self._broadcaster.broadcast(
                Phase2bMessage(self._my_addr, self._configuration_id,
                               rnd=msg.rnd, endpoints=self._vval)
            )

    def handle_phase2b(self, msg: Phase2bMessage) -> None:
        """Everyone counts phase2b votes per rank; decide past majority.
        Paxos.java:223-238."""
        if msg.configuration_id != self._configuration_id:
            return
        in_rnd = self._accept_responses.setdefault(msg.rnd, {})
        in_rnd[msg.sender] = msg
        if len(in_rnd) > self._n // 2 and not self._decided:
            self._decided = True
            self._on_decide(list(msg.endpoints))

    def register_fast_round_vote(self, vote: Sequence[Endpoint]) -> None:
        """Record our own fast-round vote; rank (1, 1). Paxos.java:246-260."""
        if self._rnd.round > 1:
            return
        self._rnd = Rank(1, 1)
        self._vrnd = self._rnd
        self._vval = tuple(vote)

    # -- value selection ----------------------------------------------------

    def select_proposal_using_coordinator_rule(
            self, phase1b_messages: Sequence[Phase1bMessage]) -> Proposal:
        """Fast Paxos Fig. 2 value-selection rule. Paxos.java:271-328.

        Order-sensitive details preserved from the reference: candidate vvals
        are scanned in message-arrival order, and a value is picked once its
        cumulative occurrence count exceeds N/4 (integer division).
        """
        if not phase1b_messages:
            raise ValueError("phase1b_messages was empty")
        max_vrnd = max(m.vrnd for m in phase1b_messages)

        # V = all vvals voted at the highest vrnd in the quorum.
        collected_vvals: List[Proposal] = [
            tuple(m.vval) for m in phase1b_messages
            if m.vrnd == max_vrnd and len(m.vval) > 0
        ]
        chosen: Optional[Proposal] = None

        if len(set(collected_vvals)) == 1:
            chosen = collected_vvals[0]
        elif len(collected_vvals) > 1:
            counters: Dict[Proposal, int] = {}
            for value in collected_vvals:
                count = counters.setdefault(value, 0)
                if count + 1 > self._n // 4:
                    chosen = value
                    break
                counters[value] = count + 1

        if chosen is None:
            chosen = next(
                (tuple(m.vval) for m in phase1b_messages if len(m.vval) > 0), ()
            )
        return chosen


class FastPaxos:
    """Fast-round consensus wrapper. FastPaxos.java:44-208."""

    def __init__(self, my_addr: Endpoint, configuration_id: int,
                 membership_size: int, client: IMessagingClient,
                 broadcaster: IBroadcaster, scheduler: IScheduler,
                 on_decide: Callable[[List[Endpoint]], None],
                 fallback_base_delay_ticks: int = 10,
                 tick_ms: int = 100, rng=None) -> None:
        self._my_addr = my_addr
        self._configuration_id = configuration_id
        self._n = membership_size
        self._broadcaster = broadcaster
        self._scheduler = scheduler
        self._fallback_base_delay_ticks = fallback_base_delay_ticks
        self._tick_ms = tick_ms
        self._rng = rng
        self._votes_per_proposal: Dict[Proposal, int] = {}
        self._votes_received: set[Endpoint] = set()
        self._decided = False
        self._scheduled_classic_round_task: Optional[object] = None
        self._on_decide_external = on_decide
        self.paxos = Paxos(my_addr, configuration_id, membership_size, client,
                           broadcaster, self._on_decided_wrapped)

    # -- decision funnel ----------------------------------------------------

    def _on_decided_wrapped(self, hosts: List[Endpoint]) -> None:
        """FastPaxos.java:78-85.

        Idempotent: a straggler's classic fallback round can complete after
        the fast round already decided here (the reference has an `assert`
        which is disabled in production Java; a duplicate decision must be
        ignored, not crash or re-fire the view change).
        """
        if self._decided:
            return
        self._decided = True
        if self._scheduled_classic_round_task is not None:
            self._scheduler.cancel(self._scheduled_classic_round_task)
            self._scheduled_classic_round_task = None
        self._on_decide_external(hosts)

    # -- proposer -----------------------------------------------------------

    def propose(self, proposal: Sequence[Endpoint],
                recovery_delay_ticks: Optional[int] = None) -> None:
        """Vote in the fast round and arm the classic-round fallback timer.
        FastPaxos.java:94-117."""
        self.paxos.register_fast_round_vote(tuple(proposal))
        self._broadcaster.broadcast(
            FastRoundPhase2bMessage(self._my_addr, self._configuration_id,
                                    tuple(proposal))
        )
        if recovery_delay_ticks is None:
            recovery_delay_ticks = self.get_random_delay_ticks()
        self._scheduled_classic_round_task = self._scheduler.schedule(
            recovery_delay_ticks, self.start_classic_paxos_round
        )

    def get_random_delay_ticks(self) -> int:
        """Base delay + expovariate jitter with rate 1/N (FastPaxos.java:200-203)."""
        u = self._rng.random() if self._rng is not None else 0.5
        jitter_ms = -1000.0 * math.log(1.0 - u) * self._n
        return self._fallback_base_delay_ticks + max(0, round(jitter_ms / self._tick_ms))

    # -- acceptor -----------------------------------------------------------

    def _handle_fast_round_proposal(self, msg: FastRoundPhase2bMessage) -> None:
        """Count fast-round votes; decide at quorum N - floor((N-1)/4).
        FastPaxos.java:125-156."""
        if msg.configuration_id != self._configuration_id:
            return
        if msg.sender in self._votes_received:
            return
        if self._decided:
            return
        self._votes_received.add(msg.sender)
        proposal = tuple(msg.endpoints)
        count = self._votes_per_proposal.get(proposal, 0) + 1
        self._votes_per_proposal[proposal] = count
        f = (self._n - 1) // 4  # Fast Paxos resiliency
        if len(self._votes_received) >= self._n - f and count >= self._n - f:
            self._on_decided_wrapped(list(msg.endpoints))

    def handle_messages(self, request) -> None:
        """Dispatch consensus messages. FastPaxos.java:163-184."""
        if isinstance(request, FastRoundPhase2bMessage):
            self._handle_fast_round_proposal(request)
        elif isinstance(request, Phase1aMessage):
            self.paxos.handle_phase1a(request)
        elif isinstance(request, Phase1bMessage):
            self.paxos.handle_phase1b(request)
        elif isinstance(request, Phase2aMessage):
            self.paxos.handle_phase2a(request)
        elif isinstance(request, Phase2bMessage):
            self.paxos.handle_phase2b(request)
        else:
            raise TypeError(f"Unexpected message: {type(request)}")

    def start_classic_paxos_round(self) -> None:
        """Fallback entry point (FastPaxos.java:189-195)."""
        if not self._decided:
            self.paxos.start_phase1a(2)
