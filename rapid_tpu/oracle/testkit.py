"""Deterministic test fixtures for driving the protocol with no transport.

Mirrors the reference's strongest correctness leverage (SURVEY.md §4.2/4.5):
- ``DirectMessagingClient`` / ``DirectBroadcaster`` deliver messages by
  calling ``handle_messages`` on the target instance directly, with a
  droppable-message-type set (PaxosTests.java:424-476).
- ``ManualScheduler`` is a virtual-time scheduler driven explicitly by tests.
- ``NoOpClient`` / ``NoOpBroadcaster`` for coordinator-rule-only tests
  (PaxosTests.java:478-503).
- ``StaticFailureDetector`` marks edges faulty from a mutable blacklist
  (StaticFailureDetector.java:24-62) — deterministic failure injection via
  the public failure-detector SPI.
"""
from __future__ import annotations

import heapq
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Set, Type

from rapid_tpu.oracle.interfaces import (
    IBroadcaster,
    IEdgeFailureDetectorFactory,
    IMessagingClient,
    IScheduler,
)
from rapid_tpu.types import Endpoint, RapidRequest


class ManualScheduler(IScheduler):
    """Virtual-time scheduler; tests call advance_to()/advance_by()."""

    def __init__(self) -> None:
        self._now = 0
        self._heap: List = []
        self._seq = itertools.count()
        self._cancelled: Set[int] = set()

    def now(self) -> int:
        return self._now

    def schedule(self, delay_ticks: int, fn: Callable[[], None]) -> object:
        handle = next(self._seq)
        heapq.heappush(self._heap, (self._now + delay_ticks, handle, fn))
        return handle

    def cancel(self, handle: object) -> None:
        self._cancelled.add(handle)  # type: ignore[arg-type]

    def advance_to(self, tick: int) -> None:
        while self._heap and self._heap[0][0] <= tick:
            due, handle, fn = heapq.heappop(self._heap)
            self._now = due
            if handle in self._cancelled:
                self._cancelled.discard(handle)
            else:
                fn()
        self._now = tick

    def advance_by(self, ticks: int) -> None:
        self.advance_to(self._now + ticks)


class DirectMessagingClient(IMessagingClient):
    """Synchronously delivers to registered handler objects by endpoint."""

    def __init__(self, instances: Dict[Endpoint, object],
                 drop_types: Optional[Set[Type]] = None) -> None:
        self.instances = instances
        self.drop_types = drop_types if drop_types is not None else set()

    def _deliver(self, remote: Endpoint, request: RapidRequest) -> None:
        if type(request) in self.drop_types:
            return
        target = self.instances.get(remote)
        if target is not None:
            target.handle_messages(request)

    def send_message(self, remote, request, on_response=None) -> None:
        self._deliver(remote, request)

    def send_message_best_effort(self, remote, request, on_response=None) -> None:
        self._deliver(remote, request)


class DirectBroadcaster(IBroadcaster):
    def __init__(self, instances: Dict[Endpoint, object],
                 client: DirectMessagingClient) -> None:
        self._instances = instances
        self._client = client

    def broadcast(self, request: RapidRequest) -> None:
        if type(request) in self._client.drop_types:
            return
        for endpoint in list(self._instances):
            self._client.send_message(endpoint, request)

    def set_membership(self, recipients: Sequence[Endpoint]) -> None:
        pass


class NoOpClient(IMessagingClient):
    def send_message(self, remote, request, on_response=None) -> None:
        pass

    def send_message_best_effort(self, remote, request, on_response=None) -> None:
        pass


class NoOpBroadcaster(IBroadcaster):
    def broadcast(self, request: RapidRequest) -> None:
        pass

    def set_membership(self, recipients: Sequence[Endpoint]) -> None:
        pass


class StaticFailureDetector(IEdgeFailureDetectorFactory):
    """An edge detector whose failed set is a mutable blacklist."""

    def __init__(self, failed_nodes: Optional[Set[Endpoint]] = None) -> None:
        self.failed_nodes: Set[Endpoint] = failed_nodes if failed_nodes is not None else set()

    def add_failed_nodes(self, nodes: Sequence[Endpoint]) -> None:
        self.failed_nodes.update(nodes)

    def create_instance(self, subject: Endpoint,
                        notify: Callable[[], None]) -> Callable[[], None]:
        # Re-notifies on every FD interval while blacklisted, like the
        # reference (StaticFailureDetector.java:39-44) — repeated alerts are
        # deduplicated downstream by the cut detector.
        def run() -> None:
            if subject in self.failed_nodes:
                notify()

        return run
