"""Deterministic tick-driven simulation substrate (virtual network + clock).

This is the oracle counterpart of the reference's runtime substrate
(SharedResources.java thread pools + gRPC transport, SURVEY.md §2.4/2.5),
collapsed into one single-threaded discrete-event engine over virtual time:

- One tick = one alert-batching window (Settings.tick_ms, default 100 ms).
- A message sent in tick t is delivered in tick t+1, subject to the fault
  model evaluated at delivery time; replies travel the same way.
- Requests that expect a reply get a timeout: if no reply arrives within
  ``rpc_timeout_ticks`` the response callback fires with None (the analog of
  the reference's per-message-type gRPC deadlines, GrpcClient.java:194-203).
- Probes take a synchronous fast path (``probe()``): the reference's probe
  timeout equals one FD interval, so evaluating reachability at probe time
  is equivalent and is exactly what the TPU kernel engine does.

Everything in a tick runs in a canonical deterministic order:
(1) message deliveries in send order, (2) scheduled tasks in schedule order.
The kernel engine reproduces this order bit-for-bit (SURVEY.md §7 "hard
parts": canonical intra-round alert order).
"""
from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from rapid_tpu.faults import HEALTHY, FaultModel
from rapid_tpu.oracle.interfaces import IMessagingClient, IScheduler
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    ProbeResponse,
    ProbeStatus,
    RapidRequest,
)

ReplyFn = Callable[[object], None]
# A server handler receives (request, reply) and may call reply now or later.
ServerHandler = Callable[[RapidRequest, ReplyFn], None]

# Consensus message classes tracked per phase for the fallback differential
# (rapid_tpu.engine.diff.run_fallback_differential). Kept separate from
# NetworkCounters so the existing total-parity checks are untouched.
CONSENSUS_PHASES = ("fast_vote", "phase1a", "phase1b", "phase2a", "phase2b")


def consensus_phase_of(request: RapidRequest) -> Optional[str]:
    """Phase key for a consensus message, None for everything else."""
    from rapid_tpu.types import (FastRoundPhase2bMessage, Phase1aMessage,
                                 Phase1bMessage, Phase2aMessage,
                                 Phase2bMessage)
    if isinstance(request, FastRoundPhase2bMessage):
        return "fast_vote"
    if isinstance(request, Phase1aMessage):
        return "phase1a"
    if isinstance(request, Phase1bMessage):
        return "phase1b"
    if isinstance(request, Phase2aMessage):
        return "phase2a"
    if isinstance(request, Phase2bMessage):
        return "phase2b"
    return None


def empty_consensus_counters() -> Dict[str, int]:
    return {f"{p}_{kind}": 0
            for p in CONSENSUS_PHASES for kind in ("sent", "delivered")}


@dataclass
class NetworkCounters:
    """Message accounting, used by the engine differential for per-tick
    message-count parity (``rapid_tpu.engine.diff``).

    ``sent`` counts ``send()`` calls (probes take the synchronous fast path
    and are tallied separately); ``delivered``/``dropped`` partition the
    messages that came due; ``timeouts`` counts response callbacks fired
    with None.
    """

    sent: int = 0
    delivered: int = 0
    dropped: int = 0
    timeouts: int = 0
    probes_sent: int = 0
    probes_failed: int = 0

    def snapshot(self) -> "NetworkCounters":
        return NetworkCounters(**self.as_dict())

    def delta(self, since: "NetworkCounters") -> "NetworkCounters":
        return NetworkCounters(**{k: v - getattr(since, k)
                                  for k, v in self.as_dict().items()})

    def as_dict(self) -> Dict[str, int]:
        return {
            "sent": self.sent,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "timeouts": self.timeouts,
            "probes_sent": self.probes_sent,
            "probes_failed": self.probes_failed,
        }


class SimScheduler(IScheduler):
    """Deterministic virtual-time scheduler shared by all simulated nodes."""

    def __init__(self) -> None:
        self._now = 0
        self._heap: List[Tuple[int, int, Callable[[], None]]] = []
        self._seq = itertools.count()
        self._cancelled: set[int] = set()

    def now(self) -> int:
        return self._now

    def schedule(self, delay_ticks: int, fn: Callable[[], None]) -> object:
        handle = next(self._seq)
        heapq.heappush(self._heap, (self._now + max(0, delay_ticks), handle, fn))
        return handle

    def cancel(self, handle: object) -> None:
        self._cancelled.add(handle)  # type: ignore[arg-type]

    def _run_due(self, tick: int) -> None:
        while self._heap and self._heap[0][0] <= tick:
            _, handle, fn = heapq.heappop(self._heap)
            if handle in self._cancelled:
                self._cancelled.discard(handle)
            else:
                fn()

    def _advance(self, tick: int) -> None:
        self._now = tick


class SimNetwork:
    """The virtual network: registered node servers + in-flight messages."""

    def __init__(self, settings: Settings, fault_model: FaultModel = HEALTHY) -> None:
        self.settings = settings
        self.fault_model = fault_model
        self.scheduler = SimScheduler()
        self._seq = itertools.count()
        # deliver_tick -> [(seq, src, dst, request, reply_to_src or None)]
        self._in_flight: Dict[int, List] = {}
        self._servers: Dict[Endpoint, "SimServer"] = {}
        self.rpc_timeout_ticks = 2
        self.counters = NetworkCounters()       # cumulative
        self.last_tick_counters = NetworkCounters()  # delta of the last step()
        # Per-tick counter deltas, one dict per step() in order — the
        # oracle half of the telemetry layer's unified TickMetrics stream
        # (rapid_tpu.telemetry.metrics.oracle_metrics).
        self.tick_history: List[Dict[str, int]] = []
        # Per-phase consensus message accounting (cumulative + per-tick),
        # network-level: a message to a kicked-but-registered node still
        # counts as delivered, exactly like NetworkCounters.delivered.
        self.consensus_counters: Dict[str, int] = empty_consensus_counters()
        self.consensus_history: List[Dict[str, int]] = []

    @property
    def tick(self) -> int:
        return self.scheduler.now()

    @property
    def message_counter(self) -> int:
        """Total messages sent (back-compat alias for ``counters.sent``)."""
        return self.counters.sent

    # -- registration --------------------------------------------------------

    def register(self, server: "SimServer") -> None:
        self._servers[server.address] = server

    def deregister(self, address: Endpoint) -> None:
        self._servers.pop(address, None)

    def server_of(self, address: Endpoint) -> Optional["SimServer"]:
        return self._servers.get(address)

    # -- sending -------------------------------------------------------------

    def send(self, src: Endpoint, dst: Endpoint, request: RapidRequest,
             on_response: Optional[ReplyFn] = None,
             timeout_ticks: Optional[int] = None) -> None:
        """Queue a message for delivery next tick (plus any link delay).

        Delay rules are evaluated at *send* time — the latency of a link is
        a property of the wire the message entered — while crashes, link
        windows, and drops are evaluated at *delivery* time, exactly like
        both engine referees. Jittered delays can reorder consecutive
        messages on one edge; delivery within a tick stays in send order.
        """
        self.counters.sent += 1
        phase = consensus_phase_of(request)
        if phase is not None:
            self.consensus_counters[f"{phase}_sent"] += 1
        delay = self.fault_model.delay_of(src, dst, self.tick)
        deliver_at = self.tick + 1 + delay
        self._in_flight.setdefault(deliver_at, []).append(
            (next(self._seq), src, dst, request, on_response)
        )
        if on_response is not None:
            # Arm the timeout; a delivered reply cancels it by marking done.
            state = {"done": False}
            entry = self._in_flight[deliver_at][-1]
            if timeout_ticks is None:
                timeout_ticks = self.rpc_timeout_ticks
            def timeout(state=state, cb=on_response):
                if not state["done"]:
                    state["done"] = True
                    self.counters.timeouts += 1
                    cb(None)
            # The deadline clock starts when the message hits the far end
            # of the wire: a slow link stretches the round-trip budget the
            # same way on both referees (the engines arm their reply
            # timers at delivery too).
            handle = self.scheduler.schedule(timeout_ticks + 1 + delay, timeout)
            # Replace the callback with a once-only wrapper that defuses the timeout.
            def once(resp, state=state, cb=on_response, handle=handle):
                if not state["done"]:
                    state["done"] = True
                    self.scheduler.cancel(handle)
                    cb(resp)
            self._in_flight[deliver_at][-1] = (entry[0], src, dst, request, once)

    def probe(self, observer: Endpoint, subject: Endpoint) -> Optional[ProbeResponse]:
        """Synchronous probe fast-path; None = probe failed (timeout/loss).

        Fault semantics are connection-oriented (like the reference's gRPC):
        ``edge_ok(src, dst)`` gates requests *initiated* by src toward dst;
        the response rides back on the initiator's connection and is not
        separately masked. This is what makes a one-way (ingress) partition
        remove exactly the partitioned node (ATC'18 §5 Fig. 9): the target
        can still probe its own subjects, while its observers cannot reach
        it."""
        t = self.tick
        fm = self.fault_model
        self.counters.probes_sent += 1
        if fm.is_crashed(subject, t) or fm.is_crashed(observer, t):
            self.counters.probes_failed += 1
            return None
        if not fm.edge_ok(observer, subject, t):
            self.counters.probes_failed += 1
            return None
        server = self._servers.get(subject)
        if server is None:
            self.counters.probes_failed += 1
            return None
        if server.service is None:
            # Server up, protocol not ready (GrpcServer.java:83-95)
            return ProbeResponse(ProbeStatus.BOOTSTRAPPING)
        return ProbeResponse(ProbeStatus.OK)

    # -- the tick loop -------------------------------------------------------

    def at(self, tick: int, fn: Callable[[], None]) -> object:
        """Schedule a host action (e.g. ``cluster.join``) at an absolute
        tick. The handle is allocated now, so actions scheduled before the
        simulation starts sort ahead of every message-processing task due
        the same tick — host operations lead the tick, deterministically."""
        assert tick >= self.tick, f"tick {tick} already passed ({self.tick})"
        return self.scheduler.schedule(tick - self.tick, fn)

    def step(self) -> None:
        """Advance one tick: deliver due messages, then run due tasks."""
        before = self.counters.snapshot()
        consensus_before = dict(self.consensus_counters)
        t = self.tick + 1
        self.scheduler._advance(t)
        for seq, src, dst, request, reply in sorted(self._in_flight.pop(t, [])):
            fm = self.fault_model
            if fm.is_crashed(src, t):
                self.counters.dropped += 1
                continue  # sender died before the message got out
            if fm.is_crashed(dst, t) or not fm.edge_ok(src, dst, t):
                self.counters.dropped += 1
                continue  # lost; any reply timeout fires later
            server = self._servers.get(dst)
            if server is None:
                self.counters.dropped += 1
                continue
            self.counters.delivered += 1
            phase = consensus_phase_of(request)
            if phase is not None:
                self.consensus_counters[f"{phase}_delivered"] += 1
            if reply is not None:
                # Route the reply back through the network (subject to faults).
                def reply_via_net(resp, src=src, dst=dst, reply=reply):
                    self._deliver_reply(dst, src, resp, reply)
                server.handle(request, reply_via_net)
            else:
                server.handle(request, lambda resp: None)
        self.scheduler._run_due(t)
        self.last_tick_counters = self.counters.delta(before)
        self.tick_history.append(self.last_tick_counters.as_dict())
        self.consensus_history.append(
            {k: v - consensus_before[k]
             for k, v in self.consensus_counters.items()})

    def _deliver_reply(self, src: Endpoint, dst: Endpoint, resp: object,
                       reply: ReplyFn) -> None:
        """Schedule a reply from src (the server) back to dst (the caller)."""
        deliver_at = self.tick + 1

        def do_deliver():
            # Replies ride the requester's established connection: only
            # crashes can lose them, not directional edge masks (see probe()).
            fm = self.fault_model
            if fm.is_crashed(src, self.tick) or fm.is_crashed(dst, self.tick):
                return  # lost; caller's timeout will fire
            reply(resp)

        self.scheduler.schedule(deliver_at - self.tick, do_deliver)

    def run_ticks(self, n: int) -> None:
        for _ in range(n):
            self.step()


class SimServer:
    """A node's server endpoint in the virtual network.

    Mirrors IMessagingServer semantics: it can be registered before the
    protocol is ready (``service is None`` -> probes answer BOOTSTRAPPING,
    everything else is dropped; GrpcServer.java:53-96)."""

    def __init__(self, network: SimNetwork, address: Endpoint) -> None:
        self.network = network
        self.address = address
        self.service = None  # set via set_membership_service

    def start(self) -> None:
        self.network.register(self)

    def shutdown(self) -> None:
        self.network.deregister(self.address)

    def set_membership_service(self, service) -> None:
        self.service = service

    def handle(self, request: RapidRequest, reply: ReplyFn) -> None:
        from rapid_tpu.types import ProbeMessage
        if self.service is None:
            if isinstance(request, ProbeMessage):
                reply(ProbeResponse(ProbeStatus.BOOTSTRAPPING))
            return  # drop everything else until the service is wired
        self.service.handle_message(request, reply)


class SimMessagingClient(IMessagingClient):
    """IMessagingClient over the virtual network (one per node).

    Join-protocol requests get the long deadline, everything else the default
    — mirroring the reference's per-message-type gRPC deadlines of 5 s for
    joins vs 1 s default (GrpcClient.java:194-203): a phase-2 join reply is
    parked at the gatekeeper until consensus completes, so it must outlive
    the batching + consensus pipeline."""

    def __init__(self, network: SimNetwork, address: Endpoint) -> None:
        self._network = network
        self.address = address

    def _timeout_for(self, request: RapidRequest) -> int:
        from rapid_tpu.types import JoinMessage, PreJoinMessage
        if isinstance(request, (JoinMessage, PreJoinMessage)):
            return self._network.settings.join_timeout_ticks
        return self._network.rpc_timeout_ticks

    def send_message(self, remote: Endpoint, request: RapidRequest,
                     on_response: Optional[ReplyFn] = None) -> None:
        self._network.send(self.address, remote, request, on_response,
                           timeout_ticks=self._timeout_for(request))

    def send_message_best_effort(self, remote: Endpoint, request: RapidRequest,
                                 on_response: Optional[ReplyFn] = None) -> None:
        self._network.send(self.address, remote, request, on_response,
                           timeout_ticks=self._timeout_for(request))
