"""rapid-tpu: a TPU-native framework with the capabilities of Rapid.

Rapid (USENIX ATC'18, reference Java implementation at /root/reference) is a
distributed membership service: processes monitor each other over a K-ring
expander overlay, detect multi-node cuts via H/L watermarks, and agree on every
membership change through leaderless Fast Paxos with a classic-Paxos fallback.

This framework provides those capabilities TPU-first: instead of N JVM
processes exchanging RPCs, all N simulated cluster nodes advance at once as
batched message-passing kernels on TPU (JAX/XLA/pallas/pjit).  Two
implementations of one protocol spec live side by side:

- ``rapid_tpu.oracle``  — an exact-semantics, tick-driven host implementation
  of the full protocol (ground truth for differential testing, and the
  small-N product: real multi-node clusters in one process, mirroring the
  reference's in-process-transport ClusterTest setup).
- ``rapid_tpu.engine``  — the batched kernel engine: capacity-padded per-node
  state tensors, one jitted tick step for the whole cluster, fault injection
  as edge-mask tensors, sharded over a device mesh via jax.sharding.

See SURVEY.md for the reference layer map this mirrors.
"""

__version__ = "0.1.0"

from rapid_tpu.settings import Settings
from rapid_tpu.types import EdgeStatus, Endpoint, JoinStatusCode, NodeId

__all__ = [
    "Settings",
    "Endpoint",
    "NodeId",
    "EdgeStatus",
    "JoinStatusCode",
    "__version__",
]
