"""Configuration for the framework.

The reference keeps a flat Settings POJO (Settings.java:23-31) with
per-component ISettings interface views, but hardcodes K/H/L as compile-time
constants (Cluster.java:72-74). Here K/H/L, capacity, tick mapping and fault
model parameters are all first-class config, per SURVEY.md §5 ("make K/H/L,
N, fault matrices, and RNG seeds first-class config").

Time model: the simulator advances in discrete ticks. One tick corresponds to
the reference's alert batching window (100 ms, MembershipService.java:75), so
reference timers map to tick counts:

- batching window 100 ms      -> 1 tick      (flush when quiescent >= 1 tick)
- failure-detector interval 1 s -> ``fd_interval_ticks`` = 10
- consensus fallback base 1 s -> ``fallback_base_delay_ticks`` = 10 plus an
  expovariate jitter with rate 1/N ticks (FastPaxos.java:200-203)
- message latency: a message sent in tick t is delivered in tick t+1.
"""
from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class Settings:
    # --- overlay / cut detection (Cluster.java:72-74 hardcodes 10/9/4) ---
    K: int = 10
    H: int = 9
    L: int = 4

    # --- time model (see module docstring) ---
    tick_ms: int = 100
    batching_window_ticks: int = 1
    fd_interval_ticks: int = 10
    fallback_base_delay_ticks: int = 10

    # --- failure detector (PingPongFailureDetector.java:41-45) ---
    fd_failure_threshold: int = 10
    fd_bootstrap_tolerance: int = 30

    # --- join protocol (Settings.java defaults: join timeout 5000ms, 5 tries)
    join_attempts: int = 5
    join_timeout_ticks: int = 50

    # --- leave (MembershipService.java:78) ---
    leave_timeout_ticks: int = 15

    # --- engine capacity / scale knobs ---
    capacity: int = 0           # 0 = derive from initial membership + joiners
    max_configs: int = 4        # config ring-buffer depth on device
    max_proposals: int = 4      # distinct consensus values tracked per config
    max_cut_size: int = 64      # max nodes per view-change proposal
    max_active_dsts: int = 128  # alert destinations tracked per config

    # --- per-receiver link-fault mode (rapid_tpu.engine.receiver) ---
    # Hard cap on the slot capacity a per-receiver fleet member may boot
    # with. The per-receiver state is quadratic per member ([C, C, K]
    # report/topology tensors — ``receiver.receiver_state_bytes`` sizes
    # it exactly), so campaigns refuse oversized fleets up front with a
    # structured ``fleet.ReceiverBudgetError`` instead of letting the
    # device OOM mid-campaign. The packed carry (``rx_kernel`` below)
    # pays a fraction of the dense bytes per member, which is what makes
    # the 4096 default honest; ``fleet.check_receiver_budget`` reports
    # both figures on refusal.
    receiver_capacity_cap: int = 4096

    # Receiver scan-carry layout and deliver/aggregate kernel. Static —
    # flipping it retraces:
    #   "xla"    — the historical dense carry and XLA deliver loop; the
    #              traced jaxpr is byte-identical to the pre-knob engine.
    #   "packed" — bool planes carried as little-endian uint8 bit-planes
    #              ([C, C] -> [C, ceil(C/8)]), epochs as deltas from a
    #              shared base, obs_full recomputed from membership
    #              (``engine.rx_packed``). Bit-identical by construction:
    #              each tick unpacks, runs the unmodified dense step, and
    #              repacks.
    #   "pallas" — packed carry plus a hand-written pallas kernel for the
    #              deliver/aggregate hot loop over the packed planes and
    #              lazy per-edge link-window reachability (no [C, C]
    #              reachability plane is materialized). Runs in interpret
    #              mode off-TPU so CI exercises it bit-for-bit.
    rx_kernel: str = "xla"

    # Dissemination/consensus protocol variant (``rapid_tpu.variants``).
    # Static — flipping it retraces:
    #   "rapid" — the paper's all-to-all alert/vote fan-out; the traced
    #             jaxpr is byte-identical to the pre-knob engine (pinned
    #             like ``rx_kernel``).
    #   "ring"  — transport-only variant: vote tallies and cut-report
    #             delivery lower through the static ring-0 order
    #             (segmented scans / permutation gathers) and message
    #             counts become O(N) per tick (one lap up, one lap
    #             down). Decisions, config ids and protocol state stay
    #             bit-identical to "rapid".
    #   "hier"  — two-level hierarchical consensus: slots hash into
    #             G = max(2, isqrt(capacity)) seeded groups; an announce
    #             decides only when >= fast_quorum(G_nonempty) groups
    #             each reach their intra-group fast quorum. The classic
    #             Paxos fallback instance is untouched.
    protocol_variant: str = "rapid"

    # Width of the packed per-slot epoch deltas (8 or 16). Deltas that
    # would saturate the narrow dtype are clamped AND flagged
    # (``receiver.FLAG_EPOCH_DELTA_SAT``), so the fallback is explicit:
    # rerun with rx_epoch_delta_bits=16 — never silently wrong.
    rx_epoch_delta_bits: int = 8

    # Depth D of the per-receiver in-flight delivery ring: wire tensors
    # carry a leading [D] axis indexed by arrival tick, so the largest
    # extra link delay a schedule may draw (base + jitter bound) is
    # D - 1. Static — changing it retraces — and budget-checked up front:
    # ``faults.validate_schedule(ring_depth=...)`` raises a structured
    # ``DelayBudgetError`` for schedules that do not fit. Depth 1 is the
    # degenerate next-tick-only wire (no delay rules representable).
    delivery_ring_depth: int = 4

    # --- observability (rapid_tpu.engine.invariants) ---
    # Compile the on-device protocol invariant monitor into the jitted
    # step. Static: flipping it retraces; False compiles the checks out
    # entirely, so the production step pays nothing for them.
    invariant_checks: bool = False

    # --- observability (rapid_tpu.engine.recorder) ---
    # Window W of the on-device flight recorder: a bounded [W, G] ring of
    # per-tick protocol gauges plus first-occurrence tick stamps carried
    # through the jitted scan as an extra carry (see
    # ``rapid_tpu.engine.recorder``). Static: 0 (the default) compiles
    # the recorder out entirely — the scan body is byte-identical to the
    # recorder-less jaxpr, same discipline as ``invariant_checks``.
    flight_recorder_window: int = 0

    # --- streaming service (rapid_tpu.service) ---
    # Ticks per resident-engine chunk: the service re-enters the jitted
    # ``lax.scan`` with the previous chunk's final carry, so one compile
    # serves the whole stream and host I/O (metrics JSONL, checkpoints)
    # overlaps the async dispatch of the next chunk. Static — it is the
    # scan length — so changing it retraces.
    stream_chunk_ticks: int = 256

    # --- randomness ---
    seed: int = 0

    def __post_init__(self) -> None:
        if not (self.K >= 3 and self.K >= self.H >= self.L > 0):
            raise ValueError(
                f"Arguments do not satisfy K >= H >= L > 0, K >= 3: "
                f"(K: {self.K}, H: {self.H}, L: {self.L})"
            )
        if self.delivery_ring_depth < 1:
            raise ValueError(
                f"delivery_ring_depth must be >= 1, got "
                f"{self.delivery_ring_depth}")
        if self.flight_recorder_window < 0:
            raise ValueError(
                f"flight_recorder_window must be >= 0, got "
                f"{self.flight_recorder_window}")
        if self.rx_kernel not in ("xla", "packed", "pallas"):
            raise ValueError(
                f"rx_kernel must be one of 'xla', 'packed', 'pallas', "
                f"got {self.rx_kernel!r}")
        if self.protocol_variant not in ("rapid", "ring", "hier"):
            raise ValueError(
                f"protocol_variant must be one of 'rapid', 'ring', "
                f"'hier', got {self.protocol_variant!r}")
        if self.stream_chunk_ticks < 1:
            raise ValueError(
                f"stream_chunk_ticks must be >= 1, got "
                f"{self.stream_chunk_ticks}")
        if self.rx_epoch_delta_bits not in (8, 16):
            raise ValueError(
                f"rx_epoch_delta_bits must be 8 or 16, got "
                f"{self.rx_epoch_delta_bits}")

    def with_(self, **kw) -> "Settings":
        return replace(self, **kw)

    # --- derived churn-pipeline delays (rapid_tpu.engine.churn) ---------
    # All in ticks, measured against the oracle's scheduler: one hop per
    # message, alert batches flush after one quiescent batching window.

    @property
    def join_enqueue_delay_ticks(self) -> int:
        """``Cluster.join()`` call -> UP alerts enqueued at the
        gatekeepers: PreJoin hop + phase-1 reply hop + JoinMessage hop."""
        return 3

    @property
    def leave_enqueue_delay_ticks(self) -> int:
        """``leave()`` call -> DOWN alerts enqueued at the observers: one
        LeaveMessage hop."""
        return 1

    @property
    def churn_announce_delay_ticks(self) -> int:
        """Alert enqueue -> proposal announce: the batch flushes after one
        quiescent batching window and takes one hop to deliver."""
        return self.batching_window_ticks + 1

    @property
    def churn_decide_delay_ticks(self) -> int:
        """Alert enqueue -> view-change decide: announce + one vote hop."""
        return self.churn_announce_delay_ticks + 1


DEFAULT_SETTINGS = Settings()
