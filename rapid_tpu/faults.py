"""Fault-injection models (the fault-matrix library).

The reference injects faults test-side only: a blacklist failure detector,
server-side message-drop interceptors, and process kills (SURVEY.md §4.5).
Here fault injection is a first-class library shared by the host oracle and
the TPU engine:

- the oracle queries ``edge_ok(src, dst, tick)`` / ``is_crashed(node, tick)``
  per event;
- the engine materializes the same model as boolean edge-mask tensors per
  tick (``rapid_tpu.engine`` calls ``edge_mask(slot_of, tick, capacity)``).

Determinism: models are pure functions of (src, dst, tick) plus a seed —
probabilistic drops hash the (seed, src-uid, dst-uid, tick) tuple via
splitmix64, so host and device sample identical faults without sharing RNG
state.

Models mirror the ATC'18 evaluation scenarios (BASELINE.md): crashes,
probabilistic packet loss (ingress-side, "80% loss on 1% of processes"),
asymmetric one-way partitions ("firewall" rules), flip-flopping reachability
(20 s on/off), and correlated rack failure.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.oracle.membership_view import uid_of
from rapid_tpu.types import Endpoint


class FaultModel:
    """Base: a healthy network."""

    def is_crashed(self, node: Endpoint, tick: int) -> bool:
        return False

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        """Whether a message sent src -> dst delivered at ``tick`` survives."""
        return True

    # -- engine-facing: materialize masks for a slot universe ----------------

    def crash_mask(self, endpoints: Sequence[Endpoint], tick: int) -> np.ndarray:
        """bool[n]: True = crashed at tick."""
        if type(self).is_crashed is FaultModel.is_crashed:
            # Healthy base: skip the per-endpoint loop entirely.
            return np.zeros(len(endpoints), dtype=bool)
        return np.array([self.is_crashed(e, tick) for e in endpoints], dtype=bool)

    def edge_mask(self, endpoints: Sequence[Endpoint], tick: int) -> np.ndarray:
        """bool[n, n]: [s, d] True = deliverable src->dst at tick (network
        only; crashes are applied separately).

        The generic fallback evaluates ``edge_ok`` per (src, dst) pair —
        O(n^2) python calls, infeasible at engine scale (100k nodes = 1e10
        calls). Models a tick engine can drive must either not override
        ``edge_ok`` (detected here: the healthy fast path allocates one
        array) or provide an array-native ``edge_mask`` override, as the
        concrete models below do.
        """
        n = len(endpoints)
        if type(self).edge_ok is FaultModel.edge_ok:
            return np.ones((n, n), dtype=bool)
        mask = np.ones((n, n), dtype=bool)
        for i, s in enumerate(endpoints):
            for j, d in enumerate(endpoints):
                if not self.edge_ok(s, d, tick):
                    mask[i, j] = False
        return mask


HEALTHY = FaultModel()


@dataclass
class CrashFault(FaultModel):
    """Nodes crash (fail-stop) at given ticks: {endpoint: crash_tick}."""

    crashes: Dict[Endpoint, int] = field(default_factory=dict)

    def is_crashed(self, node: Endpoint, tick: int) -> bool:
        t = self.crashes.get(node)
        return t is not None and tick >= t

    def crash_mask(self, endpoints, tick):
        ticks = np.array([self.crashes.get(e, np.iinfo(np.int64).max)
                          for e in endpoints])
        return ticks <= tick


@dataclass
class PacketDropFault(FaultModel):
    """Probabilistic drop with probability p on edges into/out of a target
    set (or everywhere if no targets). ``ingress``: drop on edges *into* a
    target (the paper's ingress-loss experiment); ``egress`` likewise."""

    p: float = 0.0
    targets: Optional[FrozenSet[Endpoint]] = None
    ingress: bool = True
    egress: bool = True
    seed: int = 0

    def _applies(self, src: Endpoint, dst: Endpoint) -> bool:
        if self.targets is None:
            return True
        return (self.ingress and dst in self.targets) or \
               (self.egress and src in self.targets)

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        if not self._applies(src, dst):
            return True
        return not _bernoulli(self.seed, uid_of(src), uid_of(dst), tick, self.p)

    def edge_mask(self, endpoints, tick):
        uids = np.array([uid_of(e) for e in endpoints], dtype=np.uint64)
        drop = _bernoulli_matrix(self.seed, uids, tick, self.p)
        if self.targets is not None:
            t = np.array([e in self.targets for e in endpoints], dtype=bool)
            applies = np.zeros((len(endpoints), len(endpoints)), dtype=bool)
            if self.ingress:
                applies |= t[None, :]
            if self.egress:
                applies |= t[:, None]
            drop &= applies
        return ~drop


@dataclass
class OneWayPartitionFault(FaultModel):
    """Asymmetric 'firewall': messages from sources in ``from_set`` to
    destinations in ``to_set`` are dropped (one direction only)."""

    from_set: FrozenSet[Endpoint] = frozenset()
    to_set: FrozenSet[Endpoint] = frozenset()
    start_tick: int = 0
    end_tick: int = 1 << 62

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        if not (self.start_tick <= tick < self.end_tick):
            return True
        return not (src in self.from_set and dst in self.to_set)

    def edge_mask(self, endpoints, tick):
        n = len(endpoints)
        if not (self.start_tick <= tick < self.end_tick):
            return np.ones((n, n), dtype=bool)
        f = np.array([e in self.from_set for e in endpoints], dtype=bool)
        t = np.array([e in self.to_set for e in endpoints], dtype=bool)
        return ~(f[:, None] & t[None, :])


@dataclass
class FlipFlopFault(FaultModel):
    """Reachability of a target set oscillates: unreachable (both directions)
    for ``period_ticks``, then reachable for ``period_ticks``, repeating —
    the paper's one-way flip-flop uses an inner one-way rule."""

    targets: FrozenSet[Endpoint] = frozenset()
    period_ticks: int = 200
    start_tick: int = 0
    one_way: bool = True  # drop only *into* targets during the off phase

    def _off_phase(self, tick: int) -> bool:
        if tick < self.start_tick:
            return False
        return ((tick - self.start_tick) // self.period_ticks) % 2 == 0

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        if not self._off_phase(tick):
            return True
        if dst in self.targets and src not in self.targets:
            return False
        if not self.one_way and src in self.targets and dst not in self.targets:
            return False
        return True

    def edge_mask(self, endpoints, tick):
        n = len(endpoints)
        if not self._off_phase(tick):
            return np.ones((n, n), dtype=bool)
        t = np.array([e in self.targets for e in endpoints], dtype=bool)
        blocked = ~t[:, None] & t[None, :]
        if not self.one_way:
            blocked |= t[:, None] & ~t[None, :]
        return ~blocked


@dataclass
class ComposedFault(FaultModel):
    """Intersection of several fault models (all must allow delivery)."""

    models: List[FaultModel] = field(default_factory=list)

    def is_crashed(self, node, tick):
        return any(m.is_crashed(node, tick) for m in self.models)

    def edge_ok(self, src, dst, tick):
        return all(m.edge_ok(src, dst, tick) for m in self.models)

    def crash_mask(self, endpoints, tick):
        mask = np.zeros(len(endpoints), dtype=bool)
        for m in self.models:
            mask |= m.crash_mask(endpoints, tick)
        return mask

    def edge_mask(self, endpoints, tick):
        mask = np.ones((len(endpoints), len(endpoints)), dtype=bool)
        for m in self.models:
            mask &= m.edge_mask(endpoints, tick)
        return mask


def correlated_rack_failure(endpoints: Sequence[Endpoint], rack_of: Callable[[Endpoint], int],
                            failed_racks: Set[int], crash_tick: int) -> CrashFault:
    """All nodes in the failed racks crash simultaneously at ``crash_tick``."""
    return CrashFault({e: crash_tick for e in endpoints if rack_of(e) in failed_racks})


# ---------------------------------------------------------------------------
# Deterministic Bernoulli sampling shared host/device
# ---------------------------------------------------------------------------

_P_SCALE = float(1 << 32)


def _bernoulli(seed: int, src_uid: int, dst_uid: int, tick: int, p: float) -> bool:
    h = hashing.hash64(
        src_uid ^ hashing.hash64(dst_uid, seed=tick & hashing.MASK64),
        seed=seed ^ 0xD809F,
    )
    return (h >> 32) < int(p * _P_SCALE)


def _bernoulli_matrix(seed: int, uids: np.ndarray, tick: int, p: float) -> np.ndarray:
    """bool[n, n] of drop decisions; [s, d] matches _bernoulli(s, d)."""
    dhi, dlo = hashing.np_to_limbs(uids)
    thi, tlo = hashing.hash64_limbs(np, dhi, dlo, seed=tick & hashing.MASK64)
    th = hashing.np_from_limbs(thi, tlo)
    x = uids[:, None] ^ th[None, :]
    xhi, xlo = hashing.np_to_limbs(x.reshape(-1))
    rhi, rlo = hashing.hash64_limbs(np, xhi, xlo, seed=seed ^ 0xD809F)
    h = rhi.astype(np.uint64).reshape(len(uids), len(uids))
    return h < np.uint64(int(p * _P_SCALE))
