"""Fault-injection models (the fault-matrix library).

The reference injects faults test-side only: a blacklist failure detector,
server-side message-drop interceptors, and process kills (SURVEY.md §4.5).
Here fault injection is a first-class library shared by the host oracle and
the TPU engine:

- the oracle queries ``edge_ok(src, dst, tick)`` / ``is_crashed(node, tick)``
  per event;
- the engine materializes the same model as boolean edge-mask tensors per
  tick (``rapid_tpu.engine`` calls ``edge_mask(slot_of, tick, capacity)``).

Determinism: models are pure functions of (src, dst, tick) plus a seed —
probabilistic drops hash the (seed, src-uid, dst-uid, tick) tuple via
splitmix64, so host and device sample identical faults without sharing RNG
state.

Models mirror the ATC'18 evaluation scenarios (BASELINE.md): crashes,
probabilistic packet loss (ingress-side, "80% loss on 1% of processes"),
asymmetric one-way partitions ("firewall" rules), flip-flopping reachability
(20 s on/off), and correlated rack failure.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from rapid_tpu import hashing
from rapid_tpu.oracle.membership_view import uid_of
from rapid_tpu.types import Endpoint


class FaultModel:
    """Base: a healthy network."""

    def is_crashed(self, node: Endpoint, tick: int) -> bool:
        return False

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        """Whether a message sent src -> dst delivered at ``tick`` survives."""
        return True

    def delay_of(self, src: Endpoint, dst: Endpoint, tick: int) -> int:
        """Extra delivery delay in ticks for a message *sent* src -> dst at
        ``tick`` (on top of the one-hop baseline). Unlike ``edge_ok``, this
        is evaluated at the send tick: the latency of a link is a property
        of when the message entered it."""
        return 0

    # -- engine-facing: materialize masks for a slot universe ----------------

    def crash_mask(self, endpoints: Sequence[Endpoint], tick: int) -> np.ndarray:
        """bool[n]: True = crashed at tick."""
        if type(self).is_crashed is FaultModel.is_crashed:
            # Healthy base: skip the per-endpoint loop entirely.
            return np.zeros(len(endpoints), dtype=bool)
        return np.array([self.is_crashed(e, tick) for e in endpoints], dtype=bool)

    def edge_mask(self, endpoints: Sequence[Endpoint], tick: int) -> np.ndarray:
        """bool[n, n]: [s, d] True = deliverable src->dst at tick (network
        only; crashes are applied separately).

        The generic fallback evaluates ``edge_ok`` per (src, dst) pair —
        O(n^2) python calls, infeasible at engine scale (100k nodes = 1e10
        calls). Models a tick engine can drive must either not override
        ``edge_ok`` (detected here: the healthy fast path allocates one
        array) or provide an array-native ``edge_mask`` override, as the
        concrete models below do.
        """
        n = len(endpoints)
        if type(self).edge_ok is FaultModel.edge_ok:
            return np.ones((n, n), dtype=bool)
        mask = np.ones((n, n), dtype=bool)
        for i, s in enumerate(endpoints):
            for j, d in enumerate(endpoints):
                if not self.edge_ok(s, d, tick):
                    mask[i, j] = False
        return mask


HEALTHY = FaultModel()


@dataclass
class CrashFault(FaultModel):
    """Nodes crash (fail-stop) at given ticks: {endpoint: crash_tick}."""

    crashes: Dict[Endpoint, int] = field(default_factory=dict)

    def is_crashed(self, node: Endpoint, tick: int) -> bool:
        t = self.crashes.get(node)
        return t is not None and tick >= t

    def crash_mask(self, endpoints, tick):
        ticks = np.array([self.crashes.get(e, np.iinfo(np.int64).max)
                          for e in endpoints])
        return ticks <= tick


@dataclass
class PacketDropFault(FaultModel):
    """Probabilistic drop with probability p on edges into/out of a target
    set (or everywhere if no targets). ``ingress``: drop on edges *into* a
    target (the paper's ingress-loss experiment); ``egress`` likewise."""

    p: float = 0.0
    targets: Optional[FrozenSet[Endpoint]] = None
    ingress: bool = True
    egress: bool = True
    seed: int = 0

    def _applies(self, src: Endpoint, dst: Endpoint) -> bool:
        if self.targets is None:
            return True
        return (self.ingress and dst in self.targets) or \
               (self.egress and src in self.targets)

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        if not self._applies(src, dst):
            return True
        return not _bernoulli(self.seed, uid_of(src), uid_of(dst), tick, self.p)

    def edge_mask(self, endpoints, tick):
        uids = np.array([uid_of(e) for e in endpoints], dtype=np.uint64)
        drop = _bernoulli_matrix(self.seed, uids, tick, self.p)
        if self.targets is not None:
            t = np.array([e in self.targets for e in endpoints], dtype=bool)
            applies = np.zeros((len(endpoints), len(endpoints)), dtype=bool)
            if self.ingress:
                applies |= t[None, :]
            if self.egress:
                applies |= t[:, None]
            drop &= applies
        return ~drop


@dataclass
class OneWayPartitionFault(FaultModel):
    """Asymmetric 'firewall': messages from sources in ``from_set`` to
    destinations in ``to_set`` are dropped (one direction only)."""

    from_set: FrozenSet[Endpoint] = frozenset()
    to_set: FrozenSet[Endpoint] = frozenset()
    start_tick: int = 0
    end_tick: int = 1 << 62

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        if not (self.start_tick <= tick < self.end_tick):
            return True
        return not (src in self.from_set and dst in self.to_set)

    def edge_mask(self, endpoints, tick):
        n = len(endpoints)
        if not (self.start_tick <= tick < self.end_tick):
            return np.ones((n, n), dtype=bool)
        f = np.array([e in self.from_set for e in endpoints], dtype=bool)
        t = np.array([e in self.to_set for e in endpoints], dtype=bool)
        return ~(f[:, None] & t[None, :])


@dataclass
class FlipFlopFault(FaultModel):
    """Reachability of a target set oscillates: unreachable (both directions)
    for ``period_ticks``, then reachable for ``period_ticks``, repeating —
    the paper's one-way flip-flop uses an inner one-way rule."""

    targets: FrozenSet[Endpoint] = frozenset()
    period_ticks: int = 200
    start_tick: int = 0
    one_way: bool = True  # drop only *into* targets during the off phase

    def _off_phase(self, tick: int) -> bool:
        if tick < self.start_tick:
            return False
        return ((tick - self.start_tick) // self.period_ticks) % 2 == 0

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        if not self._off_phase(tick):
            return True
        if dst in self.targets and src not in self.targets:
            return False
        if not self.one_way and src in self.targets and dst not in self.targets:
            return False
        return True

    def edge_mask(self, endpoints, tick):
        n = len(endpoints)
        if not self._off_phase(tick):
            return np.ones((n, n), dtype=bool)
        t = np.array([e in self.targets for e in endpoints], dtype=bool)
        blocked = ~t[:, None] & t[None, :]
        if not self.one_way:
            blocked |= t[:, None] & ~t[None, :]
        return ~blocked


@dataclass
class ComposedFault(FaultModel):
    """Intersection of several fault models (all must allow delivery)."""

    models: List[FaultModel] = field(default_factory=list)

    def is_crashed(self, node, tick):
        return any(m.is_crashed(node, tick) for m in self.models)

    def edge_ok(self, src, dst, tick):
        return all(m.edge_ok(src, dst, tick) for m in self.models)

    def delay_of(self, src, dst, tick):
        return sum(m.delay_of(src, dst, tick) for m in self.models)

    def crash_mask(self, endpoints, tick):
        mask = np.zeros(len(endpoints), dtype=bool)
        for m in self.models:
            mask |= m.crash_mask(endpoints, tick)
        return mask

    def edge_mask(self, endpoints, tick):
        mask = np.ones((len(endpoints), len(endpoints)), dtype=bool)
        for m in self.models:
            mask &= m.edge_mask(endpoints, tick)
        return mask


def correlated_rack_failure(endpoints: Sequence[Endpoint], rack_of: Callable[[Endpoint], int],
                            failed_racks: Set[int], crash_tick: int) -> CrashFault:
    """All nodes in the failed racks crash simultaneously at ``crash_tick``."""
    return CrashFault({e: crash_tick for e in endpoints if rack_of(e) in failed_racks})


# ---------------------------------------------------------------------------
# Adversary schedules: slot-indexed unscripted fault programs
# ---------------------------------------------------------------------------
#
# The adversarial differential (``engine.diff.run_adversarial_differential``)
# does not pre-approve scenarios; it takes a *schedule* — crash ticks, a set
# of directed link windows, and optional scripted consensus proposes — in
# slot coordinates and runs it through both the oracle (as a ``FaultModel``)
# and the per-receiver device engine (as window-encoded mask arrays on
# ``engine.state.EngineFaults``). ``LinkWindow`` is the single normal form
# every link-level model above lowers to: a one-way partition is one window
# with ``period_ticks=0``, a flip-flop link is one window with
# ``period_ticks>0`` (off-phase first, like ``FlipFlopFault``).

_NEVER_TICK = (1 << 31) - 1  # int32-safe "never" sentinel


@dataclass(frozen=True)
class LinkWindow:
    """One directed reachability window in slot coordinates.

    While *active* — ``start_tick <= t < end_tick`` and, when
    ``period_ticks > 0``, the flip-flop off-phase
    ``((t - start_tick) // period_ticks) % 2 == 0`` — messages delivered at
    tick ``t`` from a slot in ``src_slots`` to a slot in ``dst_slots`` are
    dropped (``two_way`` additionally drops the reverse direction). Masks
    are evaluated at the *delivery* tick, like every edge rule in this
    module.
    """

    src_slots: FrozenSet[int] = frozenset()
    dst_slots: FrozenSet[int] = frozenset()
    start_tick: int = 0
    end_tick: int = _NEVER_TICK
    period_ticks: int = 0
    two_way: bool = False

    def active(self, tick: int) -> bool:
        if not (self.start_tick <= tick < self.end_tick):
            return False
        if self.period_ticks <= 0:
            return True
        return ((tick - self.start_tick) // self.period_ticks) % 2 == 0

    def blocks(self, src_slot: int, dst_slot: int, tick: int) -> bool:
        if not self.active(tick):
            return False
        if src_slot in self.src_slots and dst_slot in self.dst_slots:
            return True
        return self.two_way and src_slot in self.dst_slots and \
            dst_slot in self.src_slots


@dataclass(frozen=True)
class DelayRule:
    """One directed per-edge latency rule in slot coordinates.

    A message *sent* at tick ``t`` (``start_tick <= t < end_tick``) from a
    slot in ``src_slots`` to a slot in ``dst_slots`` is delivered
    ``delay_ticks`` ticks later than the one-hop baseline, plus a bounded
    jitter term drawn uniformly from ``[0, jitter_ticks]`` by the shared
    seeded hash (``_delay_jitter`` — host and device sample bit-identical
    values without sharing RNG state). ``reverse_delay_ticks >= 0`` also
    delays the reverse direction by that base (slow-link asymmetry: a
    different base per direction, same jitter bound); ``-1`` leaves the
    reverse direction at the baseline. Unlike ``LinkWindow``, delay rules
    are evaluated at the *send* tick — latency is a property of when the
    message entered the link — while crash/window drops still apply at
    the delivery tick. Jittered delays on one edge reorder messages:
    receivers process them in announce order, exactly like the oracle.
    """

    src_slots: FrozenSet[int] = frozenset()
    dst_slots: FrozenSet[int] = frozenset()
    delay_ticks: int = 1
    jitter_ticks: int = 0
    reverse_delay_ticks: int = -1
    start_tick: int = 0
    end_tick: int = _NEVER_TICK

    def active(self, tick: int) -> bool:
        return self.start_tick <= tick < self.end_tick

    def max_delay(self) -> int:
        """Worst-case extra delay any edge of this rule can draw."""
        return max(self.delay_ticks,
                   max(self.reverse_delay_ticks, 0)) + self.jitter_ticks


def _delay_jitter(seed: int, src_slot: int, dst_slot: int, tick: int,
                  bound: int) -> int:
    """Uniform draw from ``[0, bound]`` for edge (src, dst) at the send
    tick. Pure function of (seed, slots, tick) — the device twin
    (``engine.monitor.delay_matrix``) computes the identical hash on
    uint32 limb pairs."""
    if bound <= 0:
        return 0
    h = hashing.hash64(
        src_slot ^ hashing.hash64(dst_slot, seed=tick & hashing.MASK64),
        seed=(seed ^ 0x6A1770) & hashing.MASK64,
    )
    return int((h >> 32) % (bound + 1))


def delay_of_slots(delays: Sequence[DelayRule], seed: int, src_slot: int,
                   dst_slot: int, tick: int) -> int:
    """Extra delivery delay for a message sent ``src -> dst`` at ``tick``.

    Per rule, the forward direction is checked before the implied reverse
    one; across rules the maximum applies (``validate_schedule`` rejects
    overlapping coverage, so at most one rule matches a given edge, but
    both referees share this exact combining order regardless).
    """
    best = 0
    for r in delays:
        if not r.active(tick):
            continue
        if src_slot in r.src_slots and dst_slot in r.dst_slots:
            base = r.delay_ticks
        elif r.reverse_delay_ticks >= 0 and src_slot in r.dst_slots \
                and dst_slot in r.src_slots:
            base = r.reverse_delay_ticks
        else:
            continue
        best = max(best, base + _delay_jitter(seed, src_slot, dst_slot,
                                              tick, r.jitter_ticks))
    return best


@dataclass(frozen=True)
class ScriptedPropose:
    """One scripted consensus propose: slot ``slot`` proposes the removal
    of ``proposal`` (ascending slot tuple) at scheduler tick ``tick`` with
    an explicit classic-fallback timer delay of ``delay_ticks``."""

    slot: int
    tick: int
    proposal: Tuple[int, ...]
    delay_ticks: int


@dataclass(frozen=True)
class AdversarySchedule:
    """A seeded, unscripted fault program over an ``n``-slot universe.

    ``crashes`` maps slot -> fail-stop tick; ``windows`` are directed link
    windows; ``proposes`` are scripted consensus proposes (mid-fast-count
    fires, tied timers and rank races arise from these plus the organic
    jittered timers — nothing here is pre-screened); ``delays`` are
    per-edge latency rules (send-tick base + seeded jitter, see
    ``DelayRule``). ``seed`` feeds the per-node jitter rng on both sides
    of the differential and the per-edge delay-jitter hash.
    """

    n: int
    crashes: Tuple[Tuple[int, int], ...] = ()
    windows: Tuple[LinkWindow, ...] = ()
    proposes: Tuple[ScriptedPropose, ...] = ()
    seed: int = 0
    delays: Tuple[DelayRule, ...] = ()

    def crash_tick_array(self) -> np.ndarray:
        ticks = np.full(self.n, _NEVER_TICK, dtype=np.int64)
        for slot, tick in self.crashes:
            ticks[slot] = min(ticks[slot], tick)
        return ticks

    def fault_model(self, endpoints: Sequence[Endpoint]) -> FaultModel:
        """The oracle-side ``FaultModel`` equivalent of this schedule."""
        crash = CrashFault({endpoints[slot]: tick
                            for slot, tick in self.crashes})
        models: List[FaultModel] = [crash, LinkWindowFault(self.windows)]
        if self.delays:
            models.append(LinkDelayFault(self.delays, self.seed))
        return ComposedFault(models)


class LinkWindowFault(FaultModel):
    """Oracle-side edge rule for a tuple of slot-indexed ``LinkWindow``s.

    Slot resolution uses the ``nX.sim`` convention of
    ``engine.diff.default_endpoints``; ``edge_mask`` is array-native so the
    engine's shared step can also drive it.
    """

    def __init__(self, windows: Sequence[LinkWindow]) -> None:
        self.windows = tuple(windows)

    @staticmethod
    def _slot(endpoint: Endpoint) -> int:
        host = endpoint.hostname
        return int(host[1:host.index(".")]) if host.startswith("n") else -1

    def edge_ok(self, src: Endpoint, dst: Endpoint, tick: int) -> bool:
        s, d = self._slot(src), self._slot(dst)
        return not any(w.blocks(s, d, tick) for w in self.windows)

    def edge_mask(self, endpoints, tick):
        n = len(endpoints)
        mask = np.ones((n, n), dtype=bool)
        slots = np.array([self._slot(e) for e in endpoints])
        for w in self.windows:
            if not w.active(tick):
                continue
            s = np.isin(slots, list(w.src_slots))
            d = np.isin(slots, list(w.dst_slots))
            blocked = s[:, None] & d[None, :]
            if w.two_way:
                blocked |= d[:, None] & s[None, :]
            mask &= ~blocked
        return mask


class LinkDelayFault(FaultModel):
    """Oracle-side latency rule for a tuple of slot-indexed ``DelayRule``s.

    Only ``delay_of`` is overridden — delay rules never drop anything, so
    ``edge_ok``/``edge_mask`` stay on the healthy fast path. Slot
    resolution follows the ``nX.sim`` convention of
    ``engine.diff.default_endpoints``, like ``LinkWindowFault``.
    """

    def __init__(self, delays: Sequence[DelayRule], seed: int) -> None:
        self.delays = tuple(delays)
        self.seed = seed

    _slot = staticmethod(LinkWindowFault._slot)

    def delay_of(self, src: Endpoint, dst: Endpoint, tick: int) -> int:
        return delay_of_slots(self.delays, self.seed, self._slot(src),
                              self._slot(dst), tick)


class DelayBudgetError(ValueError):
    """A delay rule's worst case does not fit the delivery ring.

    The device lowers delays to a bounded in-flight ring of
    ``Settings.delivery_ring_depth`` slots indexed by arrival tick, so the
    largest representable extra delay is ``ring_depth - 1``. Structured
    like ``fleet.ReceiverBudgetError``: refuse up front with the measured
    numbers instead of silently wrapping the ring mid-run.
    """

    def __init__(self, ring_depth: int, max_delay: int, base_ticks: int,
                 jitter_ticks: int) -> None:
        self.ring_depth = ring_depth
        self.max_delay = max_delay
        self.base_ticks = base_ticks
        self.jitter_ticks = jitter_ticks
        super().__init__(
            f"delay rule can draw up to {max_delay} extra ticks (base "
            f"{base_ticks} + jitter {jitter_ticks}) but the delivery ring "
            f"holds at most {ring_depth - 1} (depth {ring_depth}); raise "
            f"Settings.delivery_ring_depth or shrink the rule")


def link_windows_of(model: FaultModel,
                    endpoints: Sequence[Endpoint]) -> Optional[List[LinkWindow]]:
    """Lower a ``FaultModel``'s link-level rules to ``LinkWindow`` normal
    form (slot coordinates follow ``endpoints`` order), or ``None`` when the
    model has edge rules no window set reproduces exactly (probabilistic
    drops)."""
    slot_of = {e: i for i, e in enumerate(endpoints)}

    def slots(es) -> FrozenSet[int]:
        return frozenset(slot_of[e] for e in es if e in slot_of)

    if isinstance(model, ComposedFault):
        out: List[LinkWindow] = []
        for m in model.models:
            sub = link_windows_of(m, endpoints)
            if sub is None:
                return None
            out += sub
        return out
    if isinstance(model, LinkWindowFault):
        return list(model.windows)
    if isinstance(model, OneWayPartitionFault):
        return [LinkWindow(src_slots=slots(model.from_set),
                           dst_slots=slots(model.to_set),
                           start_tick=model.start_tick,
                           end_tick=min(model.end_tick, _NEVER_TICK))]
    if isinstance(model, FlipFlopFault):
        t = slots(model.targets)
        others = frozenset(range(len(endpoints))) - t
        return [LinkWindow(src_slots=others, dst_slots=t,
                           start_tick=model.start_tick,
                           period_ticks=model.period_ticks,
                           two_way=not model.one_way)]
    if isinstance(model, (CrashFault,)) or type(model) is FaultModel:
        return []  # no edge rules
    return None


def validate_schedule(schedule: AdversarySchedule,
                      ring_depth: Optional[int] = None) -> None:
    """Genuine input validation only — nothing scenario-shaped is rejected.

    Slots must exist, crashes and proposes must land at tick >= 1 (tick 0
    is the boot snapshot), proposals must be non-empty ascending slot
    tuples, explicit delays non-negative, and at most one scripted propose
    per slot (the device schedule carries one scripted timer slot per node
    next to the organic one). Delay rules must have sane fields and
    non-overlapping directed-edge coverage (including each rule's implied
    reverse direction). When ``ring_depth`` is given — receiver-mode
    lowering passes ``Settings.delivery_ring_depth`` — any rule whose
    worst-case draw (base + jitter bound) exceeds ``ring_depth - 1``
    raises ``DelayBudgetError`` instead of silently wrapping the ring.
    """
    n = schedule.n
    for slot, tick in schedule.crashes:
        if not 0 <= slot < n:
            raise ValueError(f"crash slot {slot} outside universe of {n}")
        if tick < 1:
            raise ValueError(f"crash tick {tick} must be >= 1")
    for w in schedule.windows:
        if not w.src_slots or not w.dst_slots:
            raise ValueError("window src_slots/dst_slots must be non-empty")
        for s in w.src_slots | w.dst_slots:
            if not 0 <= s < n:
                raise ValueError(f"window slot {s} outside universe of {n}")
        if w.period_ticks < 0:
            raise ValueError("window period_ticks must be >= 0")
        if w.start_tick >= w.end_tick:
            raise ValueError(
                f"zero-length window: start_tick {w.start_tick} >= "
                f"end_tick {w.end_tick}")
    # Two *static* (period 0) windows may not both cover the same
    # directed edge in overlapping tick ranges: the duplicate edge rule
    # is at best redundant and at worst a half-healed partition the
    # author didn't mean (flip-flop windows are exempt — phase offsets
    # make simultaneous coverage intentional there).
    static = [w for w in schedule.windows if w.period_ticks == 0]
    for i, a in enumerate(static):
        for b in static[i + 1:]:
            if a.start_tick >= b.end_tick or b.start_tick >= a.end_tick:
                continue
            a_dirs = [(a.src_slots, a.dst_slots)] + (
                [(a.dst_slots, a.src_slots)] if a.two_way else [])
            b_dirs = [(b.src_slots, b.dst_slots)] + (
                [(b.dst_slots, b.src_slots)] if b.two_way else [])
            for asrc, adst in a_dirs:
                for bsrc, bdst in b_dirs:
                    if (asrc & bsrc) and (adst & bdst):
                        s = min(asrc & bsrc)
                        d = min(adst & bdst)
                        raise ValueError(
                            f"overlapping static windows cover directed "
                            f"edge {s}->{d} in ticks "
                            f"[{max(a.start_tick, b.start_tick)}, "
                            f"{min(a.end_tick, b.end_tick)})")
    per_slot: Dict[int, int] = {}
    seen: Set[Tuple[int, int]] = set()
    for p in schedule.proposes:
        if not 0 <= p.slot < n:
            raise ValueError(f"propose slot {p.slot} outside universe of {n}")
        if p.tick < 1:
            raise ValueError(f"propose tick {p.tick} must be >= 1")
        if not p.proposal or list(p.proposal) != sorted(set(p.proposal)):
            raise ValueError("proposal must be a non-empty ascending tuple")
        if any(not 0 <= s < n for s in p.proposal):
            raise ValueError("proposal slot outside universe")
        if p.delay_ticks < 0:
            raise ValueError("delay_ticks must be >= 0")
        if (p.slot, p.tick) in seen:
            raise ValueError(f"two scripted proposes on slot {p.slot} at "
                             f"tick {p.tick}")
        seen.add((p.slot, p.tick))
        per_slot[p.slot] = per_slot.get(p.slot, 0) + 1
        if per_slot[p.slot] > 1:
            raise ValueError(f"more than one scripted propose on slot "
                             f"{p.slot} (device schedule capacity)")
    for r in schedule.delays:
        if not r.src_slots or not r.dst_slots:
            raise ValueError("delay src_slots/dst_slots must be non-empty")
        for s in r.src_slots | r.dst_slots:
            if not 0 <= s < n:
                raise ValueError(f"delay slot {s} outside universe of {n}")
        if r.delay_ticks < 0:
            raise ValueError("delay_ticks must be >= 0")
        if r.jitter_ticks < 0:
            raise ValueError("jitter_ticks must be >= 0")
        if r.reverse_delay_ticks < -1:
            raise ValueError("reverse_delay_ticks must be >= -1 "
                             "(-1 means no reverse delay)")
        if r.start_tick >= r.end_tick:
            raise ValueError(
                f"zero-length delay rule: start_tick {r.start_tick} >= "
                f"end_tick {r.end_tick}")
        if ring_depth is not None and r.max_delay() > ring_depth - 1:
            raise DelayBudgetError(
                ring_depth=ring_depth, max_delay=r.max_delay(),
                base_ticks=max(r.delay_ticks, r.reverse_delay_ticks),
                jitter_ticks=r.jitter_ticks)
    # Two delay rules may not cover the same directed edge in overlapping
    # tick ranges (counting each rule's implied reverse direction): the
    # referees take the max, so the overlap would silently mask the
    # smaller rule — reject so schedules stay composable-by-inspection.
    delay_rules = list(schedule.delays)
    for i, a in enumerate(delay_rules):
        for b in delay_rules[i + 1:]:
            if a.start_tick >= b.end_tick or b.start_tick >= a.end_tick:
                continue
            a_dirs = [(a.src_slots, a.dst_slots)] + (
                [(a.dst_slots, a.src_slots)]
                if a.reverse_delay_ticks >= 0 else [])
            b_dirs = [(b.src_slots, b.dst_slots)] + (
                [(b.dst_slots, b.src_slots)]
                if b.reverse_delay_ticks >= 0 else [])
            for asrc, adst in a_dirs:
                for bsrc, bdst in b_dirs:
                    if (asrc & bsrc) and (adst & bdst):
                        s = min(asrc & bsrc)
                        d = min(adst & bdst)
                        raise ValueError(
                            f"overlapping delay rules cover directed "
                            f"edge {s}->{d} in ticks "
                            f"[{max(a.start_tick, b.start_tick)}, "
                            f"{min(a.end_tick, b.end_tick)})")


def random_adversary_schedule(n: int, seed: int, ticks: int,
                              fd_interval: int = 10) -> AdversarySchedule:
    """Sample an unscripted fault schedule: a crash burst that may straddle
    an FD-interval boundary, a one-way partition of a random ring subset,
    and (sometimes) a flip-flop link window. Deterministic in ``seed``."""
    import random as _random

    rng = _random.Random(seed)
    crashes: List[Tuple[int, int]] = []
    n_crash = rng.randint(1, max(1, n // 16))
    burst_start = rng.randint(1, max(1, fd_interval))
    for slot in rng.sample(range(n), n_crash):
        # Half the crashes land after the next FD boundary -> straddling.
        tick = burst_start + (fd_interval if rng.random() < 0.5 else 0)
        crashes.append((slot, tick))
    windows: List[LinkWindow] = []
    if rng.random() < 0.75:
        size = rng.randint(2, max(2, n // 4))
        iso = frozenset(rng.sample(range(n), size))
        rest = frozenset(range(n)) - iso
        windows.append(LinkWindow(src_slots=rest, dst_slots=iso,
                                  start_tick=rng.randint(1, fd_interval)))
    if rng.random() < 0.25:
        size = rng.randint(1, max(1, n // 8))
        t = frozenset(rng.sample(range(n), size))
        windows.append(LinkWindow(
            src_slots=frozenset(range(n)) - t, dst_slots=t,
            start_tick=rng.randint(1, ticks // 2),
            period_ticks=rng.randint(2, 4) * fd_interval))
    schedule = AdversarySchedule(n=n, crashes=tuple(sorted(crashes)),
                                 windows=tuple(windows), seed=seed)
    validate_schedule(schedule)
    return schedule


@dataclass(frozen=True)
class ScenarioWeights:
    """Sampling weights over the scenario-space kinds of
    ``sample_adversary_schedule``. Zero removes a kind; weights need not
    normalize. The default mix exercises every kind.

    Field names are one-to-one with ``SCENARIO_KINDS`` (and, for the
    latency family, ``DELAY_KINDS``) — asserted by
    ``tests/test_variants.py`` — so adding a kind means adding a field
    here, a branch in the sampler, and an entry in the kind table."""

    crash: float = 1.0
    partition: float = 1.0
    flip_flop: float = 1.0
    contested: float = 1.0
    churn: float = 1.0
    delay: float = 1.0
    jitter: float = 1.0
    slow_asym: float = 1.0

    def items(self) -> Tuple[Tuple[str, float], ...]:
        pairs = (("crash", self.crash), ("partition", self.partition),
                 ("flip_flop", self.flip_flop), ("contested", self.contested),
                 ("churn", self.churn), ("delay", self.delay),
                 ("jitter", self.jitter), ("slow_asym", self.slow_asym))
        out = tuple((k, w) for k, w in pairs if w > 0)
        if not out:
            raise ValueError("all scenario weights are zero")
        return out


DEFAULT_SCENARIO_WEIGHTS = ScenarioWeights()

#: Every kind `sample_adversary_schedule` can draw, in ScenarioWeights
#: field order — campaign forced-weight sweeps iterate this.
SCENARIO_KINDS = ("crash", "partition", "flip_flop", "contested", "churn",
                  "delay", "jitter", "slow_asym")

#: The latency-family subset: members whose schedule carries DelayRules.
DELAY_KINDS = ("delay", "jitter", "slow_asym")


@dataclass(frozen=True)
class SampledScenario:
    """One draw from scenario space: the fault program plus the sampled
    kind and whether the campaign should pair it with a churn schedule
    (churn lives in ``engine.churn.ChurnSchedule``, outside the
    ``AdversarySchedule`` surface the host referee replays)."""

    kind: str
    schedule: AdversarySchedule
    wants_churn: bool = False


def _sample_crash_burst(rng, n: int, fd_interval: int) -> List[Tuple[int, int]]:
    crashes: List[Tuple[int, int]] = []
    n_crash = rng.randint(1, max(1, n // 16))
    burst_start = rng.randint(1, max(1, fd_interval))
    for slot in rng.sample(range(n), n_crash):
        tick = burst_start + (fd_interval if rng.random() < 0.5 else 0)
        crashes.append((slot, tick))
    return sorted(crashes)


def sample_adversary_schedule(
        n: int, seed: int, ticks: int,
        weights: Optional[ScenarioWeights] = None,
        fd_interval: int = 10, ring_depth: int = 4) -> SampledScenario:
    """Seeded scenario-space sampler for Monte-Carlo fleet campaigns.

    Draws a scenario *kind* from ``weights`` — the full kind table is
    ``SCENARIO_KINDS``, in ``ScenarioWeights`` field order:

    - ``crash``      — one correlated crash burst;
    - ``partition``  — an isolated subset (sometimes healing mid-run,
      sometimes with a crash burst on top);
    - ``flip_flop``  — a periodically flapping link window;
    - ``contested``  — 2-3 camps proposing conflicting removals with
      explicit fallback delays (no fast quorum, classic round recovers);
    - ``churn``      — join/leave traffic (``wants_churn=True``; the
      churn schedule itself lives in ``engine.churn.ChurnSchedule``,
      outside the ``AdversarySchedule`` surface), sometimes under a
      light crash;
    - ``delay`` / ``jitter`` / ``slow_asym`` — the latency family
      (``DELAY_KINDS``): fixed slow subsets, bounded per-message jitter,
      and asymmetric slow links, all bounded by ``ring_depth`` and paired
      with a crash burst so each regime exercises a view change.

    Knob fills (burst sizes, subsets, periods, camp splits, delay bounds)
    come from the same ``random.Random(seed)`` stream — fully
    deterministic in ``seed``. Every returned schedule passes
    ``validate_schedule`` with the given ``ring_depth`` (property-tested
    in ``tests/test_fleet.py``). ``tests/test_variants.py`` asserts the
    ``ScenarioWeights`` field names match ``SCENARIO_KINDS`` so this
    table cannot drift from the sampler again.
    ``random_adversary_schedule`` above is the fixed crash+partition mix
    the adversary tests pin; this sampler is the campaign-facing superset.
    """
    import random as _random

    weights = weights or DEFAULT_SCENARIO_WEIGHTS
    rng = _random.Random(seed)
    pairs = weights.items()
    kind = rng.choices([k for k, _ in pairs], [w for _, w in pairs])[0]

    crashes: List[Tuple[int, int]] = []
    windows: List[LinkWindow] = []
    proposes: List[ScriptedPropose] = []
    delays: List[DelayRule] = []
    wants_churn = False
    if kind == "crash":
        crashes = _sample_crash_burst(rng, n, fd_interval)
    elif kind == "partition":
        size = rng.randint(2, max(2, n // 3))
        iso = frozenset(rng.sample(range(n), size))
        rest = frozenset(range(n)) - iso
        end = _NEVER_TICK
        if rng.random() < 0.3:  # sometimes the partition heals mid-run
            end = max(2, ticks // 2)
        windows.append(LinkWindow(
            src_slots=rest, dst_slots=iso,
            start_tick=rng.randint(1, fd_interval), end_tick=end,
            two_way=rng.random() < 0.3))
        if rng.random() < 0.5:
            crashes = _sample_crash_burst(rng, n, fd_interval)
    elif kind == "flip_flop":
        size = rng.randint(1, max(1, n // 8))
        t = frozenset(rng.sample(range(n), size))
        windows.append(LinkWindow(
            src_slots=frozenset(range(n)) - t, dst_slots=t,
            start_tick=rng.randint(1, max(1, ticks // 2)),
            period_ticks=rng.randint(1, 4) * fd_interval,
            two_way=rng.random() < 0.5))
        if rng.random() < 0.3:
            crashes = _sample_crash_burst(rng, n, fd_interval)
    elif kind == "contested":
        # Split the electorate into camps proposing conflicting removals:
        # no camp reaches the fast quorum, timers with explicit delays
        # fire, and the classic-Paxos fallback recovers.
        n_camps = rng.randint(2, 3)
        victims = sorted(rng.sample(range(n), n_camps))
        tick0 = rng.randint(2, max(2, fd_interval))
        for slot in range(n):
            camp = rng.randrange(n_camps)
            proposes.append(ScriptedPropose(
                slot=slot, tick=tick0, proposal=(victims[camp],),
                delay_ticks=rng.randint(1, 3 * fd_interval)))
    elif kind == "churn":
        wants_churn = True
        if rng.random() < 0.4:  # churn under a light late crash
            slot = rng.randrange(n)
            crashes = [(slot, rng.randint(1, max(1, fd_interval)))]
    elif kind == "delay":
        # A fixed-latency slow subset: every message into (and, half the
        # time, out of) the subset arrives `base` ticks late. No jitter,
        # so ordering is preserved — the pure tail-latency regime.
        size = rng.randint(1, max(1, n // 4))
        slow = frozenset(rng.sample(range(n), size))
        rest = frozenset(range(n)) - slow
        base = rng.randint(1, max(1, ring_depth - 1))
        delays.append(DelayRule(
            src_slots=rest, dst_slots=slow, delay_ticks=base,
            reverse_delay_ticks=base if rng.random() < 0.5 else -1,
            start_tick=rng.randint(0, fd_interval)))
        # Every latency member pairs its rule with a crash burst so the
        # regime exercises a full view change under latency — the
        # campaign's per-regime ticks-to-first-decide tails come from
        # these decides.
        crashes = _sample_crash_burst(rng, n, fd_interval)
    elif kind == "jitter":
        # Bounded per-message jitter on a subset's inbound edges: draws
        # differ tick to tick, so consecutive messages on one edge can
        # swap arrival order — the reordering regime.
        size = rng.randint(1, max(1, n // 4))
        t = frozenset(rng.sample(range(n), size))
        jit = rng.randint(1, max(1, ring_depth - 2))
        base = rng.randint(0, ring_depth - 1 - jit)
        delays.append(DelayRule(
            src_slots=frozenset(range(n)) - t, dst_slots=t,
            delay_ticks=base, jitter_ticks=jit,
            reverse_delay_ticks=base if rng.random() < 0.5 else -1,
            start_tick=rng.randint(0, fd_interval)))
        crashes = _sample_crash_burst(rng, n, fd_interval)
    elif kind == "slow_asym":
        # Slow-link asymmetry: traffic toward one half is slower than the
        # return path (possibly instant), mimicking a congested uplink.
        half = frozenset(rng.sample(range(n), max(1, n // 2)))
        fwd = rng.randint(1, max(1, ring_depth - 1))
        rev = rng.choice([d for d in range(ring_depth) if d != fwd])
        delays.append(DelayRule(
            src_slots=frozenset(range(n)) - half, dst_slots=half,
            delay_ticks=fwd, reverse_delay_ticks=rev,
            start_tick=rng.randint(0, fd_interval)))
        crashes = _sample_crash_burst(rng, n, fd_interval)
    else:  # pragma: no cover - items() only yields the kinds above
        raise AssertionError(kind)

    schedule = AdversarySchedule(
        n=n, crashes=tuple(crashes), windows=tuple(windows),
        proposes=tuple(proposes), seed=seed, delays=tuple(delays))
    validate_schedule(schedule, ring_depth=ring_depth)
    return SampledScenario(kind=kind, schedule=schedule,
                           wants_churn=wants_churn)


def two_zone_schedule(n: int, seed: int, ticks: int,
                      ring_depth: int = 4,
                      fd_interval: int = 10) -> AdversarySchedule:
    """The named two-zone deployment scenario as a concrete schedule.

    Splits the universe into ``zone_a = [0, n//2)`` and
    ``zone_b = [n//2, n)`` — two racks behind one congested uplink:

    - intra-zone traffic is *fast* (no rule: one-hop baseline both ways);
    - cross-zone traffic gets one slow-asym ``DelayRule`` — the a->b
      direction carries the congested base, the return path a strictly
      smaller one, both directions sharing a small jitter bound so
      cross-zone messages also reorder;
    - one correlated crash burst inside ``zone_b`` (a quarter of the
      zone, same tick) — the rack-level analogue of the traffic
      generator's correlated leave bursts, forcing view changes whose
      evidence must cross the slow uplink.

    Knob draws come from ``random.Random(seed)`` so campaigns get a
    family of two-zone instances, but the zone split itself is fixed.
    The schedule is validated against ``ring_depth`` before it is
    returned — a delivery ring too shallow for the drawn worst case
    raises ``DelayBudgetError`` up front, which is how callers size
    ``Settings.delivery_ring_depth`` for this preset.
    """
    import random as _random

    if n < 4:
        raise ValueError(f"two_zone needs n >= 4 (got {n})")
    rng = _random.Random(seed)
    zone_a = frozenset(range(n // 2))
    zone_b = frozenset(range(n // 2, n))
    jitter = 1 if ring_depth >= 3 else 0
    fwd = rng.randint(2, max(2, ring_depth - 1 - jitter))
    rev = rng.randint(1, fwd - 1) if fwd > 1 else 0
    delays = (DelayRule(src_slots=zone_a, dst_slots=zone_b,
                        delay_ticks=fwd, jitter_ticks=jitter,
                        reverse_delay_ticks=rev, start_tick=0),)
    burst_tick = rng.randint(1, max(1, min(fd_interval, ticks - 1)))
    n_crash = max(1, len(zone_b) // 4)
    crashes = tuple(sorted(
        (slot, burst_tick)
        for slot in rng.sample(sorted(zone_b), n_crash)))
    schedule = AdversarySchedule(n=n, crashes=crashes, seed=seed,
                                 delays=delays)
    validate_schedule(schedule, ring_depth=ring_depth)
    return schedule


#: Named scenario mixes for campaigns. ``"two_zone"`` biases the sampler
#: toward the slow-asym latency regime with crash pressure — the weights
#: twin of the concrete ``two_zone_schedule`` instance family (which
#: differential tests validate directly at N=64).
SCENARIO_WEIGHT_PRESETS: Dict[str, ScenarioWeights] = {
    "default": DEFAULT_SCENARIO_WEIGHTS,
    "two_zone": ScenarioWeights(
        crash=1.0, partition=0.0, flip_flop=0.0, contested=0.0,
        churn=0.0, delay=0.0, jitter=0.0, slow_asym=3.0),
}


def scenario_weights_preset(name: str) -> ScenarioWeights:
    """Look up a named ``ScenarioWeights`` preset; raises with the
    catalogue on an unknown name."""
    try:
        return SCENARIO_WEIGHT_PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario-weights preset {name!r}; known presets: "
            f"{sorted(SCENARIO_WEIGHT_PRESETS)}") from None


# ---------------------------------------------------------------------------
# Deterministic Bernoulli sampling shared host/device
# ---------------------------------------------------------------------------

_P_SCALE = float(1 << 32)


def _bernoulli(seed: int, src_uid: int, dst_uid: int, tick: int, p: float) -> bool:
    h = hashing.hash64(
        src_uid ^ hashing.hash64(dst_uid, seed=tick & hashing.MASK64),
        seed=seed ^ 0xD809F,
    )
    return (h >> 32) < int(p * _P_SCALE)


def _bernoulli_matrix(seed: int, uids: np.ndarray, tick: int, p: float) -> np.ndarray:
    """bool[n, n] of drop decisions; [s, d] matches _bernoulli(s, d)."""
    dhi, dlo = hashing.np_to_limbs(uids)
    thi, tlo = hashing.hash64_limbs(np, dhi, dlo, seed=tick & hashing.MASK64)
    th = hashing.np_from_limbs(thi, tlo)
    x = uids[:, None] ^ th[None, :]
    xhi, xlo = hashing.np_to_limbs(x.reshape(-1))
    rhi, rlo = hashing.hash64_limbs(np, xhi, xlo, seed=seed ^ 0xD809F)
    h = rhi.astype(np.uint64).reshape(len(uids), len(uids))
    return h < np.uint64(int(p * _P_SCALE))
