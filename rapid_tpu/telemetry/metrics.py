"""Unified tick metrics: one record stream for engine and oracle.

The engine logs per-tick sender/recipient *factors* (``StepLog``) that
``rapid_tpu.engine.diff.expand_counters`` multiplies into exact message
tallies; the oracle tallies the same traffic directly on its virtual
network (``NetworkCounters`` deltas per ``SimNetwork.step``). This module
normalizes both into ``TickMetrics`` — the record the differential
harness compares, the forensics report quotes, and the trace exporter
renders — plus ``RunSummary``, the per-run protocol summary the
benchmarks embed in their JSON payloads.

Counter fields (``COUNTER_FIELDS``) are observable on both sides and must
agree tick-for-tick inside the crash-fault envelope. Gauge fields are
engine-side protocol observables (alert-pipeline occupancy, cut-detector
fill toward H, fast-round vote tally vs quorum, membership size, config
epoch); the oracle does not export them, so they read ``UNOBSERVED`` on
oracle records and are excluded from equality checks.
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

#: Gauge value on sources that do not observe the gauge (oracle records).
UNOBSERVED = -1

#: Fields observable on both sides; per-tick equality is asserted by the
#: differential harness inside the crash-fault envelope.
COUNTER_FIELDS = ("sent", "delivered", "dropped", "timeouts",
                  "probes_sent", "probes_failed")


@dataclass(frozen=True)
class TickMetrics:
    """One tick of one source ("engine" | "oracle"), normalized."""

    tick: int
    source: str
    # message counters (exact, host-expanded)
    sent: int = 0
    delivered: int = 0
    dropped: int = 0
    timeouts: int = 0
    probes_sent: int = 0
    probes_failed: int = 0
    # protocol gauges (engine-derived; UNOBSERVED on the oracle)
    n_member: int = UNOBSERVED
    epoch: int = UNOBSERVED
    alerts_in_flight: int = UNOBSERVED
    cut_reports: int = UNOBSERVED
    implicit_reports: int = UNOBSERVED
    vote_tally: int = UNOBSERVED
    quorum: int = UNOBSERVED
    churn_injected: int = UNOBSERVED
    # fault-context gauges: directed member edges blocked by active link
    # windows and deliveries dropped by those masks this tick, so
    # divergence forensics can name the fault context of the first
    # divergent tick. Engine-derived; UNOBSERVED on the oracle.
    partitioned_edges: int = UNOBSERVED
    link_dropped: int = UNOBSERVED
    # on-device invariant-monitor bitmask (engine.invariants.describe_bits
    # decodes it); 0 on every clean tick, constant 0 when the run was
    # compiled with Settings.invariant_checks=False, UNOBSERVED on the
    # oracle.
    invariant_violations: int = UNOBSERVED
    # consensus-fallback gauges (engine-derived; UNOBSERVED on the oracle
    # and whenever the run has no fallback schedule). The per-phase sent
    # gauges are *not* counters: the oracle's alert-path fast votes land
    # in ``sent``, so cross-side per-phase parity is asserted only by
    # ``diff.FallbackDiffResult`` against ``SimNetwork.consensus_history``.
    px_timers_armed: int = UNOBSERVED
    px_coord_round: int = UNOBSERVED
    px_fast_vote_sent: int = UNOBSERVED
    px_phase1a_sent: int = UNOBSERVED
    px_phase1b_sent: int = UNOBSERVED
    px_phase2a_sent: int = UNOBSERVED
    px_phase2b_sent: int = UNOBSERVED
    # protocol events at this tick
    announce: bool = False
    decide: bool = False

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, object]) -> "TickMetrics":
        return TickMetrics(**d)

    def counters(self) -> Dict[str, int]:
        return {f: getattr(self, f) for f in COUNTER_FIELDS}


def counters_equal(a: TickMetrics, b: TickMetrics) -> bool:
    """Equality restricted to the fields both sources observe."""
    return a.tick == b.tick and all(
        getattr(a, f) == getattr(b, f) for f in COUNTER_FIELDS)


# ---------------------------------------------------------------------------
# normalizers
# ---------------------------------------------------------------------------


def engine_metrics(logs) -> List[TickMetrics]:
    """Normalize stacked engine ``StepLog`` rows into TickMetrics.

    Counters come from ``diff.expand_counters`` (exact python-int
    products); gauges are read straight off the log's end-of-tick
    snapshot fields.
    """
    from rapid_tpu.engine.diff import expand_counters, \
        expand_fallback_counters

    counters = expand_counters(logs)
    px = expand_fallback_counters(logs)
    ticks = np.asarray(logs.tick)
    ann = np.asarray(logs.announce_now)
    dec = np.asarray(logs.decide_now)
    n_member = np.asarray(logs.n_member)
    epoch = np.asarray(logs.epoch)
    in_flight = np.asarray(logs.alerts_in_flight)
    cut_reports = np.asarray(logs.cut_reports)
    implicit = np.asarray(logs.implicit_reports)
    tally = np.asarray(logs.vote_tally)
    quorum = np.asarray(logs.quorum)
    churned = np.asarray(logs.churn_injected)
    part_edges = np.asarray(logs.partitioned_edges)
    link_dropped = np.asarray(logs.link_dropped)
    inv_bits = np.asarray(logs.inv_bits)
    timers_armed = np.asarray(logs.px_timers_armed)
    coord_round = np.asarray(logs.px_coord_round)

    out: List[TickMetrics] = []
    for i, c in enumerate(counters):
        out.append(TickMetrics(
            tick=int(ticks[i]), source="engine", **c,
            n_member=int(n_member[i]),
            epoch=int(epoch[i]),
            alerts_in_flight=int(in_flight[i]),
            cut_reports=int(cut_reports[i]),
            implicit_reports=int(implicit[i]),
            vote_tally=int(tally[i]),
            quorum=int(quorum[i]),
            churn_injected=int(churned[i]),
            partitioned_edges=int(part_edges[i]),
            link_dropped=int(link_dropped[i]),
            invariant_violations=int(inv_bits[i]),
            px_timers_armed=int(timers_armed[i]),
            px_coord_round=int(coord_round[i]),
            px_fast_vote_sent=px[i]["fast_vote_sent"],
            px_phase1a_sent=px[i]["phase1a_sent"],
            px_phase1b_sent=px[i]["phase1b_sent"],
            px_phase2a_sent=px[i]["phase2a_sent"],
            px_phase2b_sent=px[i]["phase2b_sent"],
            announce=bool(ann[i]),
            decide=bool(dec[i]),
        ))
    return out


def oracle_metrics(per_tick_counters: Sequence[Dict[str, int]],
                   events: Iterable = (),
                   start_tick: int = 0) -> List[TickMetrics]:
    """Normalize oracle ``NetworkCounters`` deltas into TickMetrics.

    ``per_tick_counters`` is what ``diff.run_oracle`` returns (one
    ``as_dict`` per tick, first entry covering ``start_tick + 1``);
    ``events`` are ``ViewEvent`` records used to flag announce/decide
    ticks. Gauges stay ``UNOBSERVED``.
    """
    ann_ticks = {e.tick for e in events if e.kind == "proposal"}
    dec_ticks = {e.tick for e in events if e.kind == "view_change"}
    out: List[TickMetrics] = []
    for i, c in enumerate(per_tick_counters):
        tick = start_tick + 1 + i
        out.append(TickMetrics(
            tick=tick, source="oracle", **c,
            announce=tick in ann_ticks, decide=tick in dec_ticks))
    return out


# ---------------------------------------------------------------------------
# JSONL round-trip
# ---------------------------------------------------------------------------


def write_jsonl(records: Iterable[TickMetrics], path) -> None:
    from rapid_tpu.telemetry import write_jsonl_artifact

    write_jsonl_artifact(path, (r.as_dict() for r in records))


def read_jsonl(path) -> List[TickMetrics]:
    out: List[TickMetrics] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(TickMetrics.from_dict(json.loads(line)))
    return out


# ---------------------------------------------------------------------------
# per-run summaries
# ---------------------------------------------------------------------------


@dataclass
class RunSummary:
    """Protocol-level summary of one simulated run (Rapid §6 observables).

    ``view_changes`` carries one record per decided proposal: its
    announce/decide ticks, ticks from the start of its window (run start
    or the previous decide) to the decision, and the exact message traffic
    attributable to that window.
    """

    source: str
    n_ticks: int
    announcements: int
    decisions: int
    ticks_to_first_announce: Optional[int]
    ticks_to_first_decide: Optional[int]
    messages_per_view_change: Optional[float]
    view_changes: List[Dict[str, object]]
    total_sent: int
    total_delivered: int
    total_dropped: int
    total_timeouts: int
    total_probes_sent: int
    total_probes_failed: int
    # ticks whose on-device invariant bitmask was nonzero (0 on clean
    # runs and whenever the monitor was compiled out; UNOBSERVED gauges
    # are excluded from the count).
    invariant_violations: int = 0
    # consensus-fallback traffic totals per phase (fast_vote, phase1a,
    # phase1b, phase2a, phase2b); all-zero when the run had no fallback
    # schedule (UNOBSERVED gauges are excluded from the sums).
    fallback_phase_sent: Dict[str, int] = field(default_factory=dict)
    # fault-context totals: peak per-tick partitioned-edge gauge and total
    # link-mask message drops over the run (0 when unobserved/healthy).
    max_partitioned_edges: int = 0
    total_link_dropped: int = 0

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


def summarize(metrics: Sequence[TickMetrics]) -> RunSummary:
    """Fold a TickMetrics stream into a RunSummary."""
    start_tick = metrics[0].tick - 1 if metrics else 0
    first_announce: Optional[int] = None
    first_decide: Optional[int] = None
    announcements = 0
    decisions = 0
    view_changes: List[Dict[str, object]] = []
    window_start = start_tick
    window_announce: Optional[int] = None
    window_sent = 0
    window_delivered = 0
    totals = dict.fromkeys(COUNTER_FIELDS, 0)
    px_fields = (("fast_vote", "px_fast_vote_sent"),
                 ("phase1a", "px_phase1a_sent"),
                 ("phase1b", "px_phase1b_sent"),
                 ("phase2a", "px_phase2a_sent"),
                 ("phase2b", "px_phase2b_sent"))
    px_totals = {phase: 0 for phase, _ in px_fields}
    inv_ticks = 0
    max_part_edges = 0
    link_dropped_total = 0

    for m in metrics:
        if m.invariant_violations > 0:
            inv_ticks += 1
        if m.partitioned_edges > max_part_edges:
            max_part_edges = m.partitioned_edges
        if m.link_dropped > 0:
            link_dropped_total += m.link_dropped
        for f in COUNTER_FIELDS:
            totals[f] += getattr(m, f)
        for phase, attr in px_fields:
            v = getattr(m, attr)
            if v >= 0:  # UNOBSERVED (oracle records) stays out of the sum
                px_totals[phase] += v
        window_sent += m.sent
        window_delivered += m.delivered
        if m.announce:
            announcements += 1
            window_announce = m.tick
            if first_announce is None:
                first_announce = m.tick
        if m.decide:
            decisions += 1
            if first_decide is None:
                first_decide = m.tick
            view_changes.append({
                "announce_tick": window_announce,
                "decide_tick": m.tick,
                "ticks_to_decide": m.tick - window_start,
                "messages_sent": window_sent,
                "messages_delivered": window_delivered,
            })
            window_start = m.tick
            window_announce = None
            window_sent = 0
            window_delivered = 0

    per_vc = (sum(v["messages_sent"] for v in view_changes)
              / len(view_changes)) if view_changes else None
    return RunSummary(
        source=metrics[0].source if metrics else "empty",
        n_ticks=len(metrics),
        announcements=announcements,
        decisions=decisions,
        ticks_to_first_announce=(first_announce - start_tick
                                 if first_announce is not None else None),
        ticks_to_first_decide=(first_decide - start_tick
                               if first_decide is not None else None),
        messages_per_view_change=per_vc,
        view_changes=view_changes,
        total_sent=totals["sent"],
        total_delivered=totals["delivered"],
        total_dropped=totals["dropped"],
        total_timeouts=totals["timeouts"],
        total_probes_sent=totals["probes_sent"],
        total_probes_failed=totals["probes_failed"],
        invariant_violations=inv_ticks,
        fallback_phase_sent=px_totals,
        max_partitioned_edges=max_part_edges,
        total_link_dropped=link_dropped_total,
    )


# ---------------------------------------------------------------------------
# fleet folds: many independent clusters, one aggregate + distributions
# ---------------------------------------------------------------------------


def fleet_summaries(logs) -> List[RunSummary]:
    """Per-member ``RunSummary`` list from member-major fleet logs.

    ``logs`` is the StepLog pytree returned by ``fleet_simulate`` — every
    field carries a leading ``[F, T, ...]`` fleet axis. Each member's
    slice runs through the exact single-run ``engine_metrics`` ->
    ``summarize`` pipeline, so fleet aggregation is a pure fold over
    per-run summaries, never a new counting rule.
    """
    fields = [np.asarray(x) for x in logs]
    cls = type(logs)
    return [summarize(engine_metrics(cls(*(x[i] for x in fields))))
            for i in range(fields[0].shape[0])]


def merge_summaries(summaries: Sequence[RunSummary],
                    source: str = "fleet") -> RunSummary:
    """Fold per-member summaries into one fleet aggregate.

    Counter-like fields (messages, announcements, decisions,
    invariant-violation ticks, per-phase fallback traffic,
    ``total_link_dropped``) sum across the fleet axis; peak gauges
    (``max_partitioned_edges``) take the max — summing a peak across
    independent clusters would fabricate an edge count no cluster ever
    saw. The semantics of every gauge are pinned in
    ``telemetry.schema.GAUGE_SEMANTICS``. ``ticks_to_first_*`` become
    the fleet-wide minima (earliest member); per-member values live in
    ``summary_distributions``. ``view_changes`` rows are dropped from
    the merge — across independent clusters they are a distribution,
    not a sequence.
    """
    if not summaries:
        raise ValueError("cannot merge an empty fleet")
    decisions = sum(s.decisions for s in summaries)
    window_sent = sum(v["messages_sent"] for s in summaries
                      for v in s.view_changes)
    firsts_a = [s.ticks_to_first_announce for s in summaries
                if s.ticks_to_first_announce is not None]
    firsts_d = [s.ticks_to_first_decide for s in summaries
                if s.ticks_to_first_decide is not None]
    phases = sorted({p for s in summaries for p in s.fallback_phase_sent})
    return RunSummary(
        source=source,
        n_ticks=max(s.n_ticks for s in summaries),
        announcements=sum(s.announcements for s in summaries),
        decisions=decisions,
        ticks_to_first_announce=min(firsts_a) if firsts_a else None,
        ticks_to_first_decide=min(firsts_d) if firsts_d else None,
        messages_per_view_change=(window_sent / decisions
                                  if decisions else None),
        view_changes=[],
        total_sent=sum(s.total_sent for s in summaries),
        total_delivered=sum(s.total_delivered for s in summaries),
        total_dropped=sum(s.total_dropped for s in summaries),
        total_timeouts=sum(s.total_timeouts for s in summaries),
        total_probes_sent=sum(s.total_probes_sent for s in summaries),
        total_probes_failed=sum(s.total_probes_failed for s in summaries),
        invariant_violations=sum(s.invariant_violations for s in summaries),
        fallback_phase_sent={
            p: sum(s.fallback_phase_sent.get(p, 0) for s in summaries)
            for p in phases},
        max_partitioned_edges=max(s.max_partitioned_edges
                                  for s in summaries),
        total_link_dropped=sum(s.total_link_dropped for s in summaries),
    )


def _nearest_rank(values: Sequence[float], pct: float) -> float:
    """Nearest-rank percentile over a non-empty sorted list —
    deterministic (no interpolation), so campaign payloads diff
    exactly."""
    idx = max(0, -(-int(pct * len(values)) // 100) - 1)
    return values[min(idx, len(values) - 1)]


def _dist(values: Sequence[float]) -> Dict[str, object]:
    vals = sorted(values)
    if not vals:
        return {"count": 0, "p50": None, "p90": None, "p99": None,
                "max": None}
    return {"count": len(vals),
            "p50": _nearest_rank(vals, 50), "p90": _nearest_rank(vals, 90),
            "p99": _nearest_rank(vals, 99), "max": vals[-1]}


def regime_distributions(
        ticks_by_regime: Dict[str, Sequence[float]]) -> Dict[str, object]:
    """Nearest-rank ``ticks_to_first_decide`` distributions keyed by
    delay regime (the campaign's schema-v6 ``delay_regimes`` block):
    regime -> the same ``{count, p50, p90, p99, max}`` shape as every
    other campaign distribution, where ``count`` is the number of
    members of that regime that decided at all."""
    return {k: _dist(v) for k, v in sorted(ticks_by_regime.items())}


def summary_distributions(
        summaries: Sequence[RunSummary]) -> Dict[str, object]:
    """Campaign distributions over per-member summaries (Rapid §6 /
    Paxos-in-the-cloud style empirical quantities): ticks-to-decide
    percentiles, message-complexity tails, invariant-violation and
    fallback rates. Percentiles are nearest-rank, so the payload is
    bit-deterministic for a fixed campaign seed."""
    n = len(summaries)
    decided = [s for s in summaries if s.ticks_to_first_decide is not None]
    fallback = [s for s in summaries
                if sum(v for p, v in s.fallback_phase_sent.items()
                       if p != "fast_vote") > 0]
    violated = [s for s in summaries if s.invariant_violations > 0]
    return {
        "clusters": n,
        "decided_clusters": len(decided),
        "decide_rate": len(decided) / n if n else None,
        "fallback_clusters": len(fallback),
        "violation_rate": len(violated) / n if n else None,
        "ticks_to_first_decide": _dist(
            [s.ticks_to_first_decide for s in decided]),
        "total_sent": _dist([s.total_sent for s in summaries]),
        "messages_per_view_change": _dist(
            [s.messages_per_view_change for s in summaries
             if s.messages_per_view_change is not None]),
        "decisions": _dist([s.decisions for s in summaries]),
    }
