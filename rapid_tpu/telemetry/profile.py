"""Per-kernel cost observatory: where does a tick's work actually go?

The ROADMAP's pjit-sharding item is gated on "timings showing which
kernel dominates at 100k". This module answers that question by lowering
each sub-kernel of the tick *separately* — topology rebuild, failure-
detector monitor, cut delivery + aggregation, fast-round vote count, and
each classic-Paxos phase (chain delivery, fast tally, phase-1a delivery,
task phase) — plus the full composed step as a reference, and reporting
for each one:

- XLA static cost analysis (``Compiled.cost_analysis()``): FLOPs and
  bytes accessed;
- XLA memory analysis (``Compiled.memory_analysis()``): argument /
  output / temp sizes and the derived peak working-set bound;
- measured wall clock: compile time plus best/median dispatch time over
  ``repeats`` timed calls of the pre-compiled executable (AOT, so the
  timings exclude tracing and cache lookups).

``dominance_report`` sweeps N (default 1k/10k/100k) and emits the
"kernel_profile_sweep" JSON payload — ``dominant_by_n`` names the
wall-clock-dominant kernel per N, ``runs[*].dominant`` additionally
names the FLOPs- and bytes-dominant kernels. The payload validates via
``rapid_tpu.telemetry.schema`` and is produced by::

    JAX_PLATFORMS=cpu python benchmarks/bench_engine.py --profile-sweep
    JAX_PLATFORMS=cpu python -m rapid_tpu.telemetry.profile --sizes 1000

The profiled state is a mid-protocol snapshot (a seeded crash burst
warmed up a few ticks), so the kernels see realistic occupancy rather
than all-zero buffers.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import statistics
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu.telemetry.schema import SCHEMA_VERSION

#: Kernel names in report order; ``full_step`` is the composed reference
#: and never picked as dominant.
KERNEL_ORDER = (
    "topology_rebuild",
    "monitor",
    "cut_aggregate",
    "vote_count",
    "paxos_chain_deliver",
    "paxos_fast_tally",
    "paxos_phase1a_deliver",
    "paxos_task_phase",
    "full_step",
)


@dataclass(frozen=True)
class KernelCost:
    """One kernel's static + measured cost at one N."""

    kernel: str
    flops: float
    bytes_accessed: float
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    peak_bytes: int
    compile_s: float
    wall_median_s: float
    wall_best_s: float
    repeats: int

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


def synthetic_state(n: int, settings, seed: int = 0,
                    warmup_ticks: int = 8, crash_frac: float = 0.01,
                    crash_tick: int = 5):
    """A mid-protocol (state, faults) pair at size ``n``.

    Same synthetic identities as ``benchmarks/bench_engine.py``; a seeded
    crash burst plus ``warmup_ticks`` of simulation leave the monitor
    counters, alert pipeline, and cut detector realistically occupied.
    """
    import jax

    from rapid_tpu import hashing
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.step import simulate

    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF ^ (seed & 0xFFFF))
    uids = hashing.np_from_limbs(hi, lo)

    state = init_state(uids, id_fp_sum=0, settings=settings)
    n_crash = max(1, int(n * crash_frac))
    crash_ticks = [I32_MAX] * n
    for slot in range(0, n, max(1, n // n_crash)):
        crash_ticks[slot] = crash_tick
    faults = crash_faults(crash_ticks)
    if warmup_ticks > 0:
        state, _ = simulate(state, faults, warmup_ticks, settings)
    jax.block_until_ready(state)
    return state, faults


def kernel_cases(state, faults, settings, fallback=None,
                 mesh=None) -> List[Tuple]:
    """(name, fn, args) for each separately-lowered sub-kernel.

    The closures mirror the call sites in ``engine/step.py`` exactly
    (same operand shapes, same derived scalars), so the per-kernel costs
    add up to the composed step's profile. ``mesh`` (static) profiles
    the slot-sharded variants — pass sharded ``state``/``faults``
    (``sharding.shard_put``) so the committed input layouts match the
    constraints the kernels re-assert.
    """
    import jax.numpy as jnp

    from rapid_tpu.engine import cut, monitor
    from rapid_tpu.engine import paxos as paxos_mod
    from rapid_tpu.engine import votes as votes_mod
    from rapid_tpu.engine.step import step as step_fn
    from rapid_tpu.engine.topology import build_topology

    def topology_rebuild(member, ring_order, ring_rank):
        return build_topology(jnp, member, ring_order, ring_rank, mesh=mesh)

    def monitor_kernel(state, faults):
        return monitor.monitor_tick(jnp, state, faults, settings)

    def cut_aggregate(state, faults):
        crashed = monitor.crashed_at(faults, state.tick + 1)
        src_alive = ~crashed
        delivered_down = cut.deliver_reports(jnp, state, src_alive)
        delivered_up = jnp.zeros_like(delivered_down)
        any_recv = (state.member & ~crashed).any()
        return cut.aggregate(jnp, state, delivered_down, delivered_up,
                             any_recv, settings, mesh=mesh)

    def vote_count(state, faults):
        crashed = monitor.crashed_at(faults, state.tick + 1)
        c = state.member.shape[0]
        n_member = state.member.sum().astype(jnp.int32)
        valid = state.voters & ~crashed & state.vote_pending
        return votes_mod.count_fast_round(
            jnp,
            jnp.broadcast_to(state.phash_hi, (c,)),
            jnp.broadcast_to(state.phash_lo, (c,)),
            valid, n_member, mesh=mesh)

    cases = [
        ("topology_rebuild", topology_rebuild,
         (state.member, state.ring_order, state.ring_rank)),
        ("monitor", monitor_kernel, (state, faults)),
        ("cut_aggregate", cut_aggregate, (state, faults)),
        ("vote_count", vote_count, (state, faults)),
    ]

    if fallback is not None:
        false_ = jnp.asarray(False)

        def paxos_chain_deliver(state, sched):
            n_member = state.member.sum().astype(jnp.int32)
            return paxos_mod.chain_deliver(jnp, state, sched,
                                           state.tick + 1, n_member,
                                           mesh=mesh)

        def paxos_fast_tally(state, sched):
            n_member = state.member.sum().astype(jnp.int32)
            return paxos_mod.fast_tally(jnp, state, sched, state.tick + 1,
                                        n_member, false_, mesh=mesh)

        def paxos_phase1a_deliver(state, sched):
            n_member = state.member.sum().astype(jnp.int32)
            return paxos_mod.phase1a_deliver(jnp, state, sched,
                                             state.tick + 1, n_member,
                                             false_, mesh=mesh)

        def paxos_task_phase(state, sched):
            n_member = state.member.sum().astype(jnp.int32)
            return paxos_mod.task_phase(jnp, state, sched, state.tick + 1,
                                        n_member, false_, mesh=mesh)

        cases += [
            ("paxos_chain_deliver", paxos_chain_deliver, (state, fallback)),
            ("paxos_fast_tally", paxos_fast_tally, (state, fallback)),
            ("paxos_phase1a_deliver", paxos_phase1a_deliver,
             (state, fallback)),
            ("paxos_task_phase", paxos_task_phase, (state, fallback)),
        ]

        def full_step(state, faults, sched):
            return step_fn(state, faults, settings, None, sched, mesh)

        cases.append(("full_step", full_step, (state, faults, fallback)))
    else:
        def full_step(state, faults):
            return step_fn(state, faults, settings, mesh=mesh)

        cases.append(("full_step", full_step, (state, faults)))
    return cases


def _cost_entry(compiled) -> Dict[str, float]:
    """Normalize ``Compiled.cost_analysis()`` across jax versions (a dict
    on some, a one-element list of dicts on others)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost if isinstance(cost, dict) else {}


def compiled_memory_stats(compiled) -> Dict[str, int]:
    """XLA ``memory_analysis`` of an AOT-compiled callable as plain ints.

    Zeros when the backend exposes no analysis (the schema treats 0 as
    "not measured" for these fields). Shared by the kernel observatory
    below and the campaign dispatch observatory
    (``engine.fleet.fleet_aot_compile``).
    """
    out = {"argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
           "peak_bytes": 0}
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return out
    if mem is None:
        return out
    arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    res = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    tmp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    alias = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    out.update(argument_bytes=arg, output_bytes=res, temp_bytes=tmp,
               peak_bytes=arg + res + tmp - alias)
    return out


def measure_kernel(name: str, fn, args, repeats: int = 5) -> KernelCost:
    """AOT-lower one kernel, read its XLA analyses, time its dispatch."""
    import jax

    t0 = time.perf_counter()
    compiled = jax.jit(fn).lower(*args).compile()
    compile_s = time.perf_counter() - t0

    cost = _cost_entry(compiled)
    mem = compiled_memory_stats(compiled)

    jax.block_until_ready(compiled(*args))  # warm the allocator
    times: List[float] = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        jax.block_until_ready(compiled(*args))
        times.append(time.perf_counter() - t0)

    return KernelCost(
        kernel=name,
        flops=float(cost.get("flops", 0.0)),
        bytes_accessed=float(cost.get("bytes accessed", 0.0)),
        argument_bytes=mem["argument_bytes"],
        output_bytes=mem["output_bytes"],
        temp_bytes=mem["temp_bytes"],
        peak_bytes=mem["peak_bytes"],
        compile_s=round(compile_s, 6),
        wall_median_s=round(statistics.median(times), 9),
        wall_best_s=round(min(times), 9),
        repeats=len(times),
    )


def profile_kernels(n: int, settings, repeats: int = 5, seed: int = 0,
                    warmup_ticks: int = 8,
                    include_fallback: bool = True) -> Dict[str, object]:
    """Profile every sub-kernel at size ``n``; returns one report entry."""
    from rapid_tpu.engine.paxos import empty_fallback_schedule

    state, faults = synthetic_state(n, settings, seed=seed,
                                    warmup_ticks=warmup_ticks)
    c = int(state.member.shape[0])
    fallback = empty_fallback_schedule(c) if include_fallback else None
    costs = [measure_kernel(name, fn, args, repeats=repeats)
             for name, fn, args in kernel_cases(state, faults, settings,
                                                fallback)]
    sub = [k for k in costs if k.kernel != "full_step"]
    dominant = {
        "wall_clock": max(sub, key=lambda k: k.wall_median_s).kernel,
        "flops": max(sub, key=lambda k: k.flops).kernel,
        "bytes": max(sub, key=lambda k: k.bytes_accessed).kernel,
    }
    full = next(k for k in costs if k.kernel == "full_step")
    sub_wall = sum(k.wall_median_s for k in sub)
    return {
        "n": n,
        "capacity": c,
        "warmup_ticks": warmup_ticks,
        "kernels": [k.as_dict() for k in costs],
        "dominant": dominant,
        # How much of the composed step the sub-kernels account for:
        # < 1 means glue (view-change cond, log assembly) matters too.
        "subkernel_wall_fraction": round(
            sub_wall / full.wall_median_s, 3) if full.wall_median_s else None,
    }


#: Kernels the multichip block compares sharded vs single-device — the
#: two the dominance report names as the scaling bottlenecks
#: (``cut_aggregate`` tops FLOPs/bytes everywhere, ``vote_count`` tops
#: wall clock at 10k/100k) plus the composed step.
MULTICHIP_KERNELS = ("cut_aggregate", "vote_count", "full_step")


def multichip_comparison(sizes: Sequence[int], settings,
                         n_devices: int = 8, repeats: int = 5,
                         seed: int = 0,
                         warmup_ticks: int = 8) -> Optional[Dict[str, object]]:
    """Sharded-vs-single-device wall clock for the dominant kernels.

    Profiles ``MULTICHIP_KERNELS`` twice per size — once single-device,
    once with inputs ``shard_put`` on an ``n_devices``-way slot mesh and
    the mesh threaded through the kernel — and reports both medians plus
    the speedup ratio. Returns ``None`` when the process has fewer than
    ``n_devices`` devices (the artifact records the absence rather than
    crashing; force devices with ``xla_force_host_platform_device_count``
    before importing jax). Sizes whose capacity does not divide the mesh
    are skipped: the sharder would replicate them anyway.

    Both sides of the comparison run in the *same* process, so they see
    the same thread budget — but note the forced-device override itself
    splits the host CPU's thread pool across the virtual devices, which
    depresses absolute wall medians relative to a clean single-device
    process (hence the ``--merge-multichip`` two-process recipe for the
    committed artifact).
    """
    import jax

    if len(jax.devices()) < n_devices:
        return None

    from rapid_tpu.engine import sharding

    mesh = sharding.slot_mesh(n_devices)
    entries: List[Dict[str, object]] = []
    for n in sizes:
        state, faults = synthetic_state(n, settings, seed=seed,
                                        warmup_ticks=warmup_ticks)
        c = int(state.member.shape[0])
        if c % n_devices:
            continue
        plain = {name: (fn, args) for name, fn, args
                 in kernel_cases(state, faults, settings)}
        s_state = sharding.shard_put(state, mesh, c)
        s_faults = sharding.shard_put(faults, mesh, c)
        sharded = {name: (fn, args) for name, fn, args
                   in kernel_cases(s_state, s_faults, settings, mesh=mesh)}
        for kname in MULTICHIP_KERNELS:
            base = measure_kernel(kname, *plain[kname], repeats=repeats)
            shrd = measure_kernel(kname, *sharded[kname], repeats=repeats)
            entries.append({
                "kernel": kname,
                "n": n,
                "single_wall_median_s": base.wall_median_s,
                "sharded_wall_median_s": shrd.wall_median_s,
                "speedup": round(
                    base.wall_median_s / shrd.wall_median_s, 3)
                if shrd.wall_median_s else None,
            })
    return {"n_devices": n_devices, "axis": sharding.AXIS,
            "kernels": entries}


def receiver_memory_block(settings, n: int = 64,
                          fleet_sizes: Sequence[int] = (4, 64),
                          seed: int = 0) -> Dict[str, object]:
    """Measured memory footprint of the per-receiver fleet step.

    AOT-lowers one vmapped ``engine.receiver.receiver_step`` tick per
    fleet size over a representative partition member (a forced
    one-way-split draw from ``sample_adversary_schedule``) and reads
    XLA's ``memory_analysis`` — the numbers that justify
    ``Settings.receiver_capacity_cap`` and that campaigns echo in their
    ``per_receiver`` payload block. ``member_state_bytes`` is the
    analytic per-member figure (``receiver.receiver_state_bytes``) the
    measured argument bytes should roughly ``F``-multiply.

    Alongside the dense measurement, the block carries a ``packed``
    twin — the same fleet widths lowered over the packed bit-plane carry
    (``engine.rx_packed``, the ``Settings.rx_kernel != "xla"`` scan
    body: unpack -> ``receiver_step`` -> repack) — plus an analytic
    ``bytes_per_member_curve`` over campaign-relevant capacities so the
    dense-vs-packed ratio is visible without re-measuring. Curve bytes
    come from ``jax.eval_shape`` over the *actual* pack function, not a
    hand-maintained table.
    """
    import jax

    from rapid_tpu.engine import receiver as receiver_mod
    from rapid_tpu.engine import rx_packed
    from rapid_tpu.engine.fleet import (lower_receiver_schedule,
                                        stack_receiver_members)
    from rapid_tpu.faults import ScenarioWeights, sample_adversary_schedule

    dense_settings = settings if settings.rx_kernel == "xla" \
        else settings.with_(rx_kernel="xla")
    packed_settings = settings if settings.rx_kernel != "xla" \
        else settings.with_(rx_kernel="packed")
    weights = ScenarioWeights(crash=0.0, partition=1.0, flip_flop=0.0,
                              contested=0.0, churn=0.0)
    sc = sample_adversary_schedule(n, seed, 8 * settings.fd_interval_ticks,
                                   weights)
    member = lower_receiver_schedule(sc.schedule, dense_settings,
                                     fleet_size=max(fleet_sizes))
    c = int(member.state.member.shape[0])

    def one_tick(state, faults):
        return receiver_mod.receiver_step(state, faults, dense_settings)

    def packed_tick(bundle, faults):
        rs = rx_packed.unpack_receiver_state(
            bundle.packed, bundle.delay_table, packed_settings)
        nxt, log = receiver_mod.receiver_step(rs, faults, packed_settings)
        return rx_packed.pack_receiver_state(nxt, packed_settings), log

    fleets: List[Dict[str, object]] = []
    packed_fleets: List[Dict[str, object]] = []
    for f in fleet_sizes:
        fleet = stack_receiver_members([member] * f)
        t0 = time.perf_counter()
        compiled = jax.jit(jax.vmap(one_tick)).lower(
            fleet.state, fleet.faults).compile()
        compile_s = time.perf_counter() - t0
        mem = compiled_memory_stats(compiled)
        fleets.append({"fleet_size": f, **mem,
                       "compile_s": round(compile_s, 6)})

        pstate = jax.vmap(
            lambda rs: rx_packed.pack_receiver_state(rs, packed_settings))(
                fleet.state)
        bundle = rx_packed.PackedReceiverBundle(
            packed=pstate, delay_table=fleet.state.delay_table)
        t0 = time.perf_counter()
        compiled_p = jax.jit(jax.vmap(packed_tick)).lower(
            bundle, fleet.faults).compile()
        compile_p_s = time.perf_counter() - t0
        mem_p = compiled_memory_stats(compiled_p)
        packed_fleets.append({"fleet_size": f, **mem_p,
                              "compile_s": round(compile_p_s, 6)})

    curve: List[Dict[str, object]] = []
    for cc in (64, 256, 1024, 4096):
        dense_b = rx_packed.dense_state_bytes(cc, dense_settings)
        packed_b = rx_packed.packed_state_bytes(cc, packed_settings)
        bundle_b = rx_packed.bundle_state_bytes(cc, packed_settings)
        curve.append({
            "capacity": cc,
            "dense_bytes": dense_b,
            "packed_carry_bytes": packed_b,
            "packed_bundle_bytes": bundle_b,
            "carry_reduction": round(dense_b / packed_b, 2),
            "bundle_reduction": round(dense_b / bundle_b, 2),
        })
    return {
        "n": n,
        "capacity": c,
        "k": settings.K,
        "member_state_bytes": receiver_mod.receiver_state_bytes(
            c, settings.K, ring_depth=settings.delivery_ring_depth),
        "member_state_bytes_packed": rx_packed.bundle_state_bytes(
            c, packed_settings),
        "fleets": fleets,
        "packed_fleets": packed_fleets,
        "bytes_per_member_curve": curve,
    }


#: Working-set budget of the protocol-variant block (bytes): sizes whose
#: dense O(N^2) reference kernel would exceed this are recorded as
#: structured refusals instead of being attempted — the block's point is
#: that the ring aggregation stays inside a laptop-class budget at
#: 1M nodes while the dense broadcast cannot.
VARIANT_BUDGET_BYTES = 2 << 30


def variant_sweep_block(settings, sizes: Sequence[int],
                        repeats: int = 3, seed: int = 0,
                        budget_bytes: int = VARIANT_BUDGET_BYTES
                        ) -> Dict[str, object]:
    """Ring-variant aggregation kernel vs the dense broadcast, per size.

    ``ring_aggregate`` is the wire kernel of ``protocol_variant="ring"``
    (``engine.votes.scan_vote_count`` under the ring permutation and its
    inverse — the exact composition ``variants.ring.ring_count_fast_round``
    lowers): O(C) state, O(C log C) work, so it *measures* at 1M nodes.
    ``dense_broadcast`` is the reference all-to-all it replaces — the
    ``[C, C]`` pairwise delivery matrix every member's vote fans out
    over. Its footprint is ``C^2`` bytes; any size where that exceeds
    ``budget_bytes`` lands in ``refusals`` with the required bytes and
    the reason, and the kernel is never lowered — a documented refusal,
    not an OOM.
    """
    import jax.numpy as jnp

    from rapid_tpu.engine import votes as votes_mod

    kernels: List[Dict[str, object]] = []
    refusals: List[Dict[str, object]] = []
    for n in sizes:
        rng = np.random.default_rng(seed ^ n)
        # Realistic vote occupancy: a few contending fingerprints over
        # most slots valid, like a contested announce mid-flight.
        pool = rng.integers(0, 2**64, 4, dtype=np.uint64)
        fps = pool[rng.integers(0, len(pool), n)]
        hi = jnp.asarray((fps >> np.uint64(32)).astype(np.uint32))
        lo = jnp.asarray((fps & np.uint64(0xFFFFFFFF)).astype(np.uint32))
        valid = jnp.asarray(rng.random(n) < 0.95)
        perm_np = rng.permutation(n).astype(np.int32)
        perm = jnp.asarray(perm_np)
        inv = jnp.asarray(np.argsort(perm_np).astype(np.int32))

        def ring_aggregate(hi, lo, valid, perm, inv):
            counts = votes_mod.scan_vote_count(
                jnp, hi[perm], lo[perm], valid[perm])[inv]
            return counts.max(), valid.sum()

        kc = measure_kernel("ring_aggregate", ring_aggregate,
                            (hi, lo, valid, perm, inv), repeats=repeats)
        kernels.append({**kc.as_dict(), "n": n})

        dense_bytes = n * n  # the [C, C] bool delivery matrix
        if dense_bytes > budget_bytes:
            refusals.append({
                "kernel": "dense_broadcast",
                "n": n,
                "bytes_required": dense_bytes,
                "budget_bytes": budget_bytes,
                "reason": (f"[C, C] pairwise delivery matrix needs "
                           f"{dense_bytes} bytes at C={n}, over the "
                           f"{budget_bytes}-byte budget — the dense "
                           f"reference cannot run at this size"),
            })
            continue

        def dense_broadcast(hi, valid):
            seen = valid[:, None] & valid[None, :]
            return seen.sum(axis=0).max(), valid.sum()

        kc = measure_kernel("dense_broadcast", dense_broadcast,
                            (hi, valid), repeats=repeats)
        kernels.append({**kc.as_dict(), "n": n})
    return {"sizes": list(sizes), "budget_bytes": budget_bytes,
            "kernels": kernels, "refusals": refusals}


def dominance_report(sizes: Sequence[int], settings, repeats: int = 5,
                     seed: int = 0, warmup_ticks: int = 8,
                     include_fallback: bool = True,
                     multichip: bool = True,
                     multichip_devices: int = 8,
                     receiver_memory: bool = True,
                     receiver_n: int = 64,
                     variant_sizes: Optional[Sequence[int]] = None
                     ) -> Dict[str, object]:
    """The ``--profile-sweep`` artifact: per-N kernel costs plus the
    wall-clock-dominant kernel per N (the pjit-sharding gate input).

    When ``multichip`` is on and enough devices exist, the payload also
    carries a ``multichip`` block with sharded-vs-single-device wall
    medians for the dominant kernels; otherwise the key is ``null`` so
    consumers can tell "not measured" from "not present". The
    ``receiver_memory`` block (same null-when-skipped convention) sizes
    the per-receiver fleet step at small and campaign-scale fleet
    widths. ``variant_sizes`` (schema v11, same null-when-skipped
    convention) profiles the ring-variant aggregation kernel against
    the dense broadcast at the listed sizes — over-budget dense sizes
    become documented refusals (``variant_sweep_block``).
    """
    import jax

    runs = [profile_kernels(n, settings, repeats=repeats, seed=seed,
                            warmup_ticks=warmup_ticks,
                            include_fallback=include_fallback)
            for n in sizes]
    return {
        "bench": "kernel_profile_sweep",
        "schema_version": SCHEMA_VERSION,
        "platform": jax.default_backend(),
        "k": settings.K,
        "sizes": list(sizes),
        "runs": runs,
        "dominant_by_n": {str(r["n"]): r["dominant"]["wall_clock"]
                          for r in runs},
        "multichip": multichip_comparison(
            sizes, settings, n_devices=multichip_devices, repeats=repeats,
            seed=seed, warmup_ticks=warmup_ticks) if multichip else None,
        "receiver_memory": receiver_memory_block(
            settings, n=receiver_n, seed=seed) if receiver_memory
        else None,
        "variants": variant_sweep_block(
            settings, variant_sizes, seed=seed) if variant_sizes
        else None,
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--sizes", type=int, nargs="+",
                        default=[1_000, 10_000, 100_000],
                        help="cluster sizes to sweep (default 1k 10k 100k)")
    parser.add_argument("--repeats", type=int, default=5,
                        help="timed dispatches per kernel (default 5)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--warmup-ticks", type=int, default=8,
                        help="simulated ticks before snapshotting the "
                             "profiled state (default 8)")
    parser.add_argument("--no-fallback", action="store_true",
                        help="skip the classic-Paxos phase kernels")
    parser.add_argument("--no-multichip", action="store_true",
                        help="skip the sharded-vs-single-device block")
    parser.add_argument("--no-receiver-memory", action="store_true",
                        help="skip the per-receiver fleet memory block")
    parser.add_argument("--receiver-n", type=int, default=64,
                        help="cluster size for the per-receiver memory "
                             "block (default 64)")
    parser.add_argument("--multichip-devices", type=int, default=8,
                        help="mesh width for the multichip block "
                             "(default 8; needs that many jax devices)")
    parser.add_argument("--variant-sizes", type=int, nargs="+",
                        default=None, metavar="N",
                        help="also profile the ring-variant aggregation "
                             "kernel vs the dense broadcast at these "
                             "sizes; dense sizes over the memory budget "
                             "are recorded as refusals, never attempted "
                             "(default: skip the block)")
    parser.add_argument("--merge-multichip", type=str, default=None,
                        metavar="REPORT",
                        help="take the multichip block from an existing "
                             "report instead of measuring it here. Forcing "
                             "xla_force_host_platform_device_count splits "
                             "the CPU thread pool across the virtual "
                             "devices and depresses every single-device "
                             "wall median, so the committed artifact is "
                             "built in two processes: the main sweep in a "
                             "clean env, the multichip block under the "
                             "forced mesh, merged with this flag")
    parser.add_argument("--out", type=str, default=None,
                        help="write the report JSON to FILE "
                             "(default: stdout)")
    args = parser.parse_args(argv)

    from rapid_tpu.settings import Settings

    report = dominance_report(args.sizes, Settings(), repeats=args.repeats,
                              seed=args.seed,
                              warmup_ticks=args.warmup_ticks,
                              include_fallback=not args.no_fallback,
                              multichip=(not args.no_multichip
                                         and args.merge_multichip is None),
                              multichip_devices=args.multichip_devices,
                              receiver_memory=not args.no_receiver_memory,
                              receiver_n=args.receiver_n,
                              variant_sizes=args.variant_sizes)
    if args.merge_multichip is not None:
        with open(args.merge_multichip) as fh:
            report["multichip"] = json.load(fh).get("multichip")
    if args.out:
        from rapid_tpu.telemetry import write_json_artifact

        write_json_artifact(args.out, report, indent=2)
    else:
        sys.stdout.write(json.dumps(report) + "\n")
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
