"""Rolling SLO windows: bounded per-chunk histograms of protocol tails.

The resident service (``rapid_tpu.service``) reports *current* latency
tails, not whole-run tails: every chunk heartbeat carries a ``slo``
block folded over the last ``window_chunks`` chunks. The machinery is
deliberately shaped like the flight recorder's gauge ring — fixed
bucket edges decided up front, bounded counts folded on-host per chunk,
nothing accumulated without bound:

- :data:`DEFAULT_BUCKET_EDGES` — power-of-two upper-inclusive tick
  edges; a sample lands in the first bucket whose edge is >= the
  sample, and the last edge is large enough that nothing overflows;
- :class:`SloWindows` — a deque of per-chunk count vectors per metric
  (``decide_latency``: announce -> decide ticks;
  ``ticks_to_view_change``: previous decide -> decide ticks, the same
  windowing ``telemetry.metrics.summarize`` uses). Percentiles are
  nearest-rank over bucket upper edges, so two hosts folding the same
  protocol stream report byte-identical p50/p95/p99;
- :class:`ViewChangeFold` / :class:`ReceiverViewChangeFold` — the
  host-side fold carries that turn chunked per-tick streams into the
  window samples. Both round-trip through ``state_dict`` so a restored
  service resumes its windows mid-fill (the checkpoint ``host`` blob
  carries them).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

#: Upper-inclusive bucket edges, in ticks. Power-of-two spacing keeps
#: the vector short while resolving both the fast path (a few ticks)
#: and pathological tails; the final edge is an effective +inf so no
#: sample ever overflows the histogram.
DEFAULT_BUCKET_EDGES = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                        2048, 4096, 1 << 30)

#: The two windowed metrics every resident stream reports.
SLO_METRICS = ("decide_latency", "ticks_to_view_change")


def _bucket_index(edges: Sequence[int], sample: int) -> int:
    for i, edge in enumerate(edges):
        if sample <= edge:
            return i
    return len(edges) - 1


def _percentile_edge(edges: Sequence[int], counts: Sequence[int],
                     pct: float) -> Optional[int]:
    """Nearest-rank percentile as a bucket upper edge (None when the
    window holds no samples). Deterministic: no interpolation, so the
    committed artifacts diff exactly."""
    total = sum(counts)
    if total == 0:
        return None
    rank = max(1, -(-int(pct * total) // 100))
    cum = 0
    for edge, count in zip(edges, counts):
        cum += count
        if cum >= rank:
            return int(edge)
    return int(edges[-1])


class SloWindows:
    """Bounded rolling histograms over the last ``window_chunks`` chunks.

    ``fold_chunk`` appends one chunk's samples per metric (evicting the
    oldest chunk once the window is full) and returns the refreshed
    ``slo`` block for that chunk's heartbeat.
    """

    def __init__(self, window_chunks: int = 8,
                 edges: Sequence[int] = DEFAULT_BUCKET_EDGES):
        if window_chunks < 1:
            raise ValueError(
                f"window_chunks must be >= 1, got {window_chunks}")
        self.window_chunks = int(window_chunks)
        self.edges = tuple(int(e) for e in edges)
        self._ring: Dict[str, deque] = {
            m: deque(maxlen=self.window_chunks) for m in SLO_METRICS}

    def fold_chunk(self, samples: Dict[str, Sequence[int]]) -> dict:
        for metric in SLO_METRICS:
            counts = [0] * len(self.edges)
            for s in samples.get(metric, ()):
                counts[_bucket_index(self.edges, int(s))] += 1
            self._ring[metric].append(counts)
        return self.block()

    def _metric_block(self, metric: str) -> dict:
        folded = [0] * len(self.edges)
        for counts in self._ring[metric]:
            for i, c in enumerate(counts):
                folded[i] += c
        return {
            "count": sum(folded),
            "counts": folded,
            "p50": _percentile_edge(self.edges, folded, 50),
            "p95": _percentile_edge(self.edges, folded, 95),
            "p99": _percentile_edge(self.edges, folded, 99),
        }

    def block(self) -> dict:
        """The heartbeat ``slo`` block (``telemetry.schema
        .SLO_WINDOW_SPEC``)."""
        return {
            "window_chunks": self.window_chunks,
            "chunks": len(self._ring[SLO_METRICS[0]]),
            "bucket_edges": list(self.edges),
            "metrics": {m: self._metric_block(m) for m in SLO_METRICS},
        }

    # --- checkpoint host blob --------------------------------------------

    def state_dict(self) -> dict:
        return {
            "kind": "slo_windows",
            "window_chunks": self.window_chunks,
            "bucket_edges": list(self.edges),
            "ring": {m: [list(c) for c in self._ring[m]]
                     for m in SLO_METRICS},
        }

    @classmethod
    def from_state(cls, state: dict) -> "SloWindows":
        slo = cls(window_chunks=state["window_chunks"],
                  edges=state["bucket_edges"])
        for metric in SLO_METRICS:
            for counts in state["ring"].get(metric, ()):
                slo._ring[metric].append([int(c) for c in counts])
        return slo


class ViewChangeFold:
    """Chunk-boundary-safe fold of a ``TickMetrics`` stream into SLO
    samples, carrying the open view-change window across chunks.

    The windowing matches ``telemetry.metrics.summarize`` exactly:
    ``ticks_to_view_change`` measures from the run start (or the
    previous decide) to the decide; ``decide_latency`` measures from
    the window's latest announce to the decide.
    """

    def __init__(self, start_tick: int = 0):
        self.window_start = int(start_tick)
        self.window_announce: Optional[int] = None

    def fold(self, rows) -> Dict[str, List[int]]:
        ttvc: List[int] = []
        latency: List[int] = []
        for m in rows:
            if m.announce:
                self.window_announce = m.tick
            if m.decide:
                ttvc.append(m.tick - self.window_start)
                if self.window_announce is not None:
                    latency.append(m.tick - self.window_announce)
                self.window_start = m.tick
                self.window_announce = None
        return {"ticks_to_view_change": ttvc, "decide_latency": latency}

    def state_dict(self) -> dict:
        return {"kind": "view_change_fold",
                "window_start": self.window_start,
                "window_announce": self.window_announce}

    @classmethod
    def from_state(cls, state: dict) -> "ViewChangeFold":
        fold = cls(start_tick=state["window_start"])
        wa = state.get("window_announce")
        fold.window_announce = None if wa is None else int(wa)
        return fold


class ReceiverViewChangeFold:
    """The per-slot twin for receiver-resident streams: every live slot
    of a per-receiver member runs its own protocol instance, so the
    window carry is per slot (``[C]`` start ticks, ``[C]`` open
    announce ticks, -1 = none). Samples come out in (tick, slot) order,
    so the fold is deterministic in the log alone."""

    def __init__(self, capacity: int, start_tick: int = 0):
        self.capacity = int(capacity)
        self.window_start = np.full(capacity, int(start_tick), np.int64)
        self.announce_tick = np.full(capacity, -1, np.int64)

    def fold(self, ticks, announce_tc, decide_tc) -> Dict[str, List[int]]:
        ticks = np.asarray(ticks)
        announce_tc = np.asarray(announce_tc, bool)
        decide_tc = np.asarray(decide_tc, bool)
        ttvc: List[int] = []
        latency: List[int] = []
        for i in range(ticks.shape[0]):
            t = int(ticks[i])
            ann = announce_tc[i]
            if ann.any():
                self.announce_tick[ann] = t
            dec = decide_tc[i]
            if not dec.any():
                continue
            ttvc.extend(int(v) for v in (t - self.window_start[dec]))
            opened = dec & (self.announce_tick >= 0)
            latency.extend(int(v) for v in (t - self.announce_tick[opened]))
            self.window_start[dec] = t
            self.announce_tick[dec] = -1
        return {"ticks_to_view_change": ttvc, "decide_latency": latency}

    def state_dict(self) -> dict:
        return {"kind": "receiver_view_change_fold",
                "capacity": self.capacity,
                "window_start": [int(v) for v in self.window_start],
                "announce_tick": [int(v) for v in self.announce_tick]}

    @classmethod
    def from_state(cls, state: dict) -> "ReceiverViewChangeFold":
        fold = cls(state["capacity"])
        fold.window_start = np.array(state["window_start"], np.int64)
        fold.announce_tick = np.array(state["announce_tick"], np.int64)
        return fold
