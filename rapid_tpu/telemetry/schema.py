"""Summary-schema validation for BENCH payloads (no external deps).

The tier-1 smoke step (``scripts/tier1.sh``) runs the root ``bench.py``
shim and validates its JSON against this schema:

    python -m rapid_tpu.telemetry.schema /path/to/bench.json

Exit code 0 means the payload carries well-typed per-run telemetry
blocks (``rapid_tpu.telemetry.metrics.RunSummary.as_dict``); a non-zero
exit prints one line per violation. Validation is structural typing by
hand — the container image has no jsonschema, and the schema is small
enough that a field->type table is clearer anyway.
"""
from __future__ import annotations

import json
import sys
from typing import Dict, List

_NUM = (int, float)
_OPT_INT = (int, type(None))

#: RunSummary.as_dict() — the per-run "telemetry" block.
TELEMETRY_SPEC = {
    "source": (str,),
    "n_ticks": (int,),
    "announcements": (int,),
    "decisions": (int,),
    "ticks_to_first_announce": _OPT_INT,
    "ticks_to_first_decide": _OPT_INT,
    "messages_per_view_change": (int, float, type(None)),
    "view_changes": (list,),
    "total_sent": (int,),
    "total_delivered": (int,),
    "total_dropped": (int,),
    "total_timeouts": (int,),
    "total_probes_sent": (int,),
    "total_probes_failed": (int,),
    "fallback_phase_sent": (dict,),
}

#: Keys of the fallback_phase_sent block (matches engine.diff._PX_CLASSES
#: and the oracle's SimNetwork consensus phases).
FALLBACK_PHASES = ("fast_vote", "phase1a", "phase1b", "phase2a", "phase2b")

VIEW_CHANGE_SPEC = {
    "announce_tick": _OPT_INT,
    "decide_tick": (int,),
    "ticks_to_decide": (int,),
    "messages_sent": (int,),
    "messages_delivered": (int,),
}

#: Required fields of one bench_engine run payload.
RUN_SPEC = {
    "bench": (str,),
    "n": (int,),
    "ticks": (int,),
    "wall_s": _NUM,
    "ticks_per_sec": _NUM,
    "rounds_per_sec": _NUM,
    "telemetry": (dict,),
}


def _check(obj: Dict, spec: Dict, where: str) -> List[str]:
    errors = []
    if not isinstance(obj, dict):
        return [f"{where}: expected an object, got {type(obj).__name__}"]
    for key, types in spec.items():
        if key not in obj:
            errors.append(f"{where}.{key}: missing")
        elif not isinstance(obj[key], types) or (
                isinstance(obj[key], bool) and bool not in types):
            errors.append(
                f"{where}.{key}: expected "
                f"{'/'.join(t.__name__ for t in types)}, "
                f"got {type(obj[key]).__name__}")
    return errors


def validate_telemetry(block, where: str = "telemetry") -> List[str]:
    errors = _check(block, TELEMETRY_SPEC, where)
    if isinstance(block, dict):
        for i, vc in enumerate(block.get("view_changes") or []):
            errors += _check(vc, VIEW_CHANGE_SPEC,
                             f"{where}.view_changes[{i}]")
        px = block.get("fallback_phase_sent")
        if isinstance(px, dict):
            errors += _check(
                px, {phase: (int,) for phase in FALLBACK_PHASES},
                f"{where}.fallback_phase_sent")
    return errors


def validate_run_payload(payload, where: str = "payload") -> List[str]:
    errors = _check(payload, RUN_SPEC, where)
    if isinstance(payload, dict) and isinstance(payload.get("telemetry"),
                                                dict):
        errors += validate_telemetry(payload["telemetry"],
                                     f"{where}.telemetry")
    return errors


def validate_bench_payload(payload) -> List[str]:
    """Validate a single-run, sweep, or suite (root ``bench.py``) payload."""
    if not isinstance(payload, dict):
        return ["payload: expected a JSON object"]
    if payload.get("bench") == "engine_tick_suite":
        errors = []
        for key in ("steady", "churn", "contested"):
            if key not in payload:
                errors.append(f"payload.{key}: missing")
            else:
                errors += validate_run_payload(payload[key],
                                               f"payload.{key}")
        return errors
    if "sweep" in payload:
        errors = []
        for i, run in enumerate(payload["sweep"]):
            errors += validate_run_payload(run, f"payload.sweep[{i}]")
        return errors
    return validate_run_payload(payload)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m rapid_tpu.telemetry.schema BENCH_JSON",
              file=sys.stderr)
        return 2
    with open(argv[0]) as fh:
        payload = json.load(fh)
    errors = validate_bench_payload(payload)
    if errors:
        for e in errors:
            print(f"schema violation: {e}", file=sys.stderr)
        return 1
    kind = payload.get("bench", "?")
    print(f"telemetry schema ok: {argv[0]} ({kind})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
