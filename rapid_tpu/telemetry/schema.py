"""Summary-schema validation for BENCH payloads (no external deps).

The tier-1 smoke step (``scripts/tier1.sh``) runs the root ``bench.py``
shim and validates its JSON against this schema:

    python -m rapid_tpu.telemetry.schema /path/to/bench.json

Exit code 0 means the payload carries well-typed per-run telemetry
blocks (``rapid_tpu.telemetry.metrics.RunSummary.as_dict``); a non-zero
exit prints one line per violation. Validation is structural typing by
hand — the container image has no jsonschema, and the schema is small
enough that a field->type table is clearer anyway.
"""
from __future__ import annotations

import json
import sys
from typing import Dict, List

_NUM = (int, float)
_OPT_INT = (int, type(None))

#: Version of the telemetry payload contract. Bumped whenever a required
#: field is added/renamed/retyped in any payload spec below; every
#: top-level BENCH artifact carries it as ``schema_version`` and
#: validation rejects a mismatch (a stale baseline or a stale validator
#: should fail loudly, not drift). Schema v12 adds the consensus-lineage
#: layer (``LINEAGE_*`` specs): campaign payloads carry a required
#: ``campaign.lineage`` block, tournament variants a per-variant lineage
#: summary, triage exemplars their span lists, and the streaming records
#: (chunk / stream_summary / status_snapshot / streaming bench run) an
#: optional last-window lineage summary.
SCHEMA_VERSION = 12

#: Protocol variants a campaign/replay payload may record
#: (``rapid_tpu.variants.VARIANTS``; kept literal here — the schema
#: module stays import-light — and pinned against the package tuple by
#: ``tests/test_variants.py``/``tests/test_telemetry.py``).
PROTOCOL_VARIANTS = ("rapid", "ring", "hier")

#: Fold semantics of every RunSummary gauge when aggregated over a fleet
#: axis (``telemetry.metrics.merge_summaries``). "total" gauges sum
#: across independent clusters; "max" gauges are per-cluster peaks where
#: a sum would fabricate a value no cluster ever observed; "min" gauges
#: are earliest-member times whose per-member spread belongs in the
#: campaign distributions instead.
GAUGE_SEMANTICS = {
    "announcements": "total",
    "decisions": "total",
    "ticks_to_first_announce": "min",
    "ticks_to_first_decide": "min",
    "total_sent": "total",
    "total_delivered": "total",
    "total_dropped": "total",
    "total_timeouts": "total",
    "total_probes_sent": "total",
    "total_probes_failed": "total",
    "invariant_violations": "total",
    "fallback_phase_sent": "total",       # per phase
    "max_partitioned_edges": "max",       # peak per-tick gauge
    "total_link_dropped": "total",
}

#: RunSummary.as_dict() — the per-run "telemetry" block.
TELEMETRY_SPEC = {
    "source": (str,),
    "n_ticks": (int,),
    "announcements": (int,),
    "decisions": (int,),
    "ticks_to_first_announce": _OPT_INT,
    "ticks_to_first_decide": _OPT_INT,
    "messages_per_view_change": (int, float, type(None)),
    "view_changes": (list,),
    "total_sent": (int,),
    "total_delivered": (int,),
    "total_dropped": (int,),
    "total_timeouts": (int,),
    "total_probes_sent": (int,),
    "total_probes_failed": (int,),
    "invariant_violations": (int,),
    "fallback_phase_sent": (dict,),
    "max_partitioned_edges": (int,),
    "total_link_dropped": (int,),
}

#: Keys of the fallback_phase_sent block (matches engine.diff._PX_CLASSES
#: and the oracle's SimNetwork consensus phases).
FALLBACK_PHASES = ("fast_vote", "phase1a", "phase1b", "phase2a", "phase2b")

VIEW_CHANGE_SPEC = {
    "announce_tick": _OPT_INT,
    "decide_tick": (int,),
    "ticks_to_decide": (int,),
    "messages_sent": (int,),
    "messages_delivered": (int,),
}

#: Required fields of one bench_engine run payload. Rates are ``null``
#: when the measured wall is below the minimum measurable floor
#: (``campaign.MIN_MEASURABLE_WALL_S``) — a sub-millisecond wall divided
#: into a tick count is noise, not a throughput figure.
RUN_SPEC = {
    "bench": (str,),
    "n": (int,),
    "ticks": (int,),
    "wall_s": _NUM,
    "ticks_per_sec": (int, float, type(None)),
    "rounds_per_sec": (int, float, type(None)),
    "telemetry": (dict,),
}

#: One per-kernel cost record of the profile observatory
#: (``rapid_tpu.telemetry.profile.KernelCost.as_dict``).
KERNEL_COST_SPEC = {
    "kernel": (str,),
    "flops": _NUM,
    "bytes_accessed": _NUM,
    "argument_bytes": (int,),
    "output_bytes": (int,),
    "temp_bytes": (int,),
    "peak_bytes": (int,),
    "compile_s": _NUM,
    "wall_median_s": _NUM,
    "wall_best_s": _NUM,
    "repeats": (int,),
}

#: One per-N entry of the dominance report.
PROFILE_RUN_SPEC = {
    "n": (int,),
    "capacity": (int,),
    "kernels": (list,),
    "dominant": (dict,),
}

#: Top level of the ``--profile-sweep`` dominance report.
PROFILE_SWEEP_SPEC = {
    "bench": (str,),
    "platform": (str,),
    "k": (int,),
    "sizes": (list,),
    "runs": (list,),
    "dominant_by_n": (dict,),
}

#: Sharded-vs-single-device block of the dominance report
#: (``rapid_tpu.telemetry.profile.multichip_comparison``). The top-level
#: ``multichip`` key may be ``null`` (not enough devices at profile
#: time); when present it must carry these fields.
MULTICHIP_SPEC = {
    "n_devices": (int,),
    "axis": (str,),
    "kernels": (list,),
}

MULTICHIP_ENTRY_SPEC = {
    "kernel": (str,),
    "n": (int,),
    "single_wall_median_s": _NUM,
    "sharded_wall_median_s": _NUM,
    "speedup": (int, float, type(None)),
}

#: Per-receiver fleet-step memory block of the dominance report
#: (``rapid_tpu.telemetry.profile.receiver_memory_block``). Like
#: ``multichip``, the top-level ``receiver_memory`` key may be ``null``
#: ("not measured"); when present it must carry these fields.
RECEIVER_MEMORY_SPEC = {
    "n": (int,),
    "capacity": (int,),
    "k": (int,),
    "member_state_bytes": (int,),
    "fleets": (list,),
}

RECEIVER_FLEET_ENTRY_SPEC = {
    "fleet_size": (int,),
    "argument_bytes": (int,),
    "output_bytes": (int,),
    "temp_bytes": (int,),
    "peak_bytes": (int,),
    "compile_s": _NUM,
}


#: Fleet-campaign block embedded in a fleet run payload under
#: ``"campaign"`` (``rapid_tpu.campaign.run_campaign``). Schema v8 adds
#: the replay identity (``n``/``ticks``/``headroom``/``weights``/
#: ``flight_recorder`` — together with ``seed``/``clusters``/
#: ``fleet_size``/``per_receiver.enabled`` they reconstruct every
#: sampled schedule and the dispatch plan bit-exactly, which is what
#: ``python -m rapid_tpu.replay`` consumes) and the ``triage`` block.
#: Schema v11 adds ``protocol_variant`` (the wire protocol every member
#: ran — replay re-derives the variant from this field alone) and the
#: optional ``tournament`` block (present only on A/B tournament runs,
#: ``campaign.run_tournament``).
CAMPAIGN_SPEC = {
    "seed": (int,),
    "protocol_variant": (str,),
    "clusters": (int,),
    "n": (int,),
    "ticks": (int,),
    "headroom": (int,),
    "weights": (dict,),
    "flight_recorder": (int,),
    "fleet_size": (int,),
    "dispatches": (int,),
    "scenario_kinds": (dict,),
    "pools": (list,),
    "per_receiver": (dict,),
    "spot_checks": (dict,),
    "distributions": (dict,),
    "delay_regimes": (dict,),
    "triage": (dict,),
    "lineage": (dict,),
}

#: Anomaly classes of the campaign triage block (schema v8), in the
#: order ``campaign._triage`` reports them. Every class key must be
#: present in ``triage.classes`` even when its count is zero — absence
#: would be indistinguishable from "classifier never ran".
TRIAGE_CLASSES = ("no_decide_by_deadline", "slow_decide",
                  "invariant_violations", "envelope_flags",
                  "excess_fallback", "spot_failures")

#: Top-level ``campaign.triage`` block (schema v8). Every value is a
#: seed-deterministic fold — no wall-clock fields — so bench_compare's
#: exact campaign diff gates the whole block. ``recorder`` is null when
#: the campaign ran without ``--flight-recorder``.
TRIAGE_SPEC = {
    "clusters": (int,),
    "flagged_members": (int,),
    "thresholds": (dict,),
    "recorder": (dict, type(None)),
    "classes": (dict,),
}

#: One anomaly class: total flagged members, per-scenario-kind counts,
#: and up to ``campaign.MAX_TRIAGE_EXEMPLARS`` exemplar refs.
TRIAGE_CLASS_SPEC = {
    "count": (int,),
    "by_kind": (dict,),
    "exemplars": (list,),
}

#: One triage exemplar: the ``(dispatch, member_index)`` ref is the
#: ``--member D:I`` handle ``rapid_tpu.replay`` takes; ``expected`` is
#: the bit-identity contract the replay must reproduce (null only for
#: forced spot-check schedules that never ran in the fleet, ref
#: ``(-1, -1)``); ``recorder`` is the member's extracted flight-recorder
#: ring (null when the campaign ran without one).
TRIAGE_EXEMPLAR_SPEC = {
    "dispatch": (int,),
    "member_index": (int,),
    "member": (int,),
    "kind": (str,),
    "mode": (str,),
    "seed": (int,),
    "expected": (dict, type(None)),
    "recorder": (dict, type(None)),
    # Schema v12: the member's lineage span list (null for forced
    # spot-check refs that never ran in the fleet).
    "lineage": (list, type(None)),
}

#: The exemplar ``expected`` block (``campaign._expected_block``): the
#: per-member fold fields a replay must match bit-for-bit.
TRIAGE_EXPECTED_SPEC = {
    "ticks_to_first_announce": _OPT_INT,
    "ticks_to_first_decide": _OPT_INT,
    "announcements": (int,),
    "decisions": (int,),
    "invariant_violations": (int,),
    "counter_totals": (dict,),
    "fallback_phase_sent": (dict,),
    "config_ids": (list,),
    "flags": (int,),
}

#: First-occurrence tick stamps of a flight-recorder payload (-1 ==
#: never observed inside the run).
RECORDER_STAMPS = ("first_announce", "first_decide", "first_fallback",
                   "first_violation")

#: One extracted flight-recorder ring
#: (``engine.recorder.recorder_payload``): the last ``window`` per-tick
#: gauge rows in chronological order (row length == len(gauges), -1 ==
#: gauge unobserved by that kernel) plus the first-occurrence stamps.
FLIGHT_RECORDER_SPEC = {
    "window": (int,),
    "gauges": (list,),
    "ticks_recorded": (int,),
    "rows": (list,),
    "stamps": (dict,),
}

#: One kind-homogeneous dispatch pool of a campaign plan (schema v7):
#: members bucketed by shape signature before stacking, so padding is
#: per-pool-tight and each pool compiles one executable. ``shape`` is
#: the pool's stacking maxima in the padding key space
#: (DISPATCH_PADDING_SPEC keys).
CAMPAIGN_POOL_SPEC = {
    "pool_id": (int,),
    "mode": (str,),
    "members": (int,),
    "dispatches": (int,),
    "fleet_size": (int,),
    "kinds": (dict,),
    "shape": (dict,),
}

#: A/B tournament block (schema v11) under ``campaign.tournament``,
#: present only when the payload came from ``campaign.run_tournament``:
#: every sampled member ran once per listed variant over identical
#: schedules/identities. All fields are seed-deterministic, so
#: ``scripts/bench_compare.py``'s exact campaign diff gates the block.
TOURNAMENT_SPEC = {
    "variants": (list,),
    "clusters": (int,),
    "per_variant": (dict,),
    "win_loss": (dict,),
}

#: One per-variant tournament row: decide counts, classic-fallback
#: member count, total wire messages, and the nearest-rank
#: decide-tick tail (one DISTRIBUTION_SPEC block).
TOURNAMENT_VARIANT_SPEC = {
    "decided": (int,),
    "fallback_members": (int,),
    "total_messages": (int,),
    "decide_ticks": (dict,),
    # Schema v12: per-variant lineage summary — the phase-duration
    # tails that show *where* a variant pays its latency.
    "lineage": (dict,),
}

#: Protocol-variant kernel block of the dominance report (schema v11,
#: ``rapid_tpu.telemetry.profile.variant_sweep_block``). Like
#: ``multichip``, the top-level ``variants`` key may be ``null``
#: ("not measured"); when present it carries the measured ring
#: aggregation kernels plus the documented dense-broadcast refusals —
#: sizes where the O(N^2) reference kernel would exceed the memory
#: budget are recorded as structured refusals, never attempted.
VARIANT_SPEC = {
    "sizes": (list,),
    "budget_bytes": (int,),
    "kernels": (list,),
    "refusals": (list,),
}

#: One documented refusal of the variant profile block: the kernel that
#: was *not* run, at which size, the bytes it would have needed against
#: the budget, and the one-line reason.
VARIANT_REFUSAL_SPEC = {
    "kernel": (str,),
    "n": (int,),
    "bytes_required": (int,),
    "budget_bytes": (int,),
    "reason": (str,),
}

#: One measured variant kernel entry: a KERNEL_COST_SPEC record plus
#: the size it ran at.
VARIANT_KERNEL_SPEC = dict(KERNEL_COST_SPEC, n=(int,))

#: Delay-regime keys the ``delay_regimes`` block may carry (schema v6):
#: the latency-family scenario kinds plus the delay-free rest of the
#: campaign. Each value is one DISTRIBUTION_SPEC block over that
#: regime's per-member ticks-to-first-decide.
DELAY_REGIMES = ("delay", "jitter", "slow_asym", "no_delay")

#: Per-receiver dispatch block of a campaign payload (schema v4): how
#: many members ran device-exact under link faults and the measured
#: quadratic budget that gated them (``receiver.receiver_state_bytes``).
PER_RECEIVER_SPEC = {
    "enabled": (bool,),
    "members": (int,),
    "dispatches": (int,),
    "fleet_size": (int,),
    "capacity": (int,),
    "capacity_cap": (int,),
    "ring_depth": (int,),
    "member_state_bytes": (int,),
    "kinds": (dict,),
}

SPOT_CHECK_SPEC = {
    "requested": (int,),
    "run": (int,),
    "passed": (int,),
    "failed": (int,),
    "max_failures": (int,),
    "members": (list,),
}

#: One spot-check member record (schema v4 adds the graceful-degradation
#: fields: mode, pass/fail, forensics artifact path, first-line error).
SPOT_MEMBER_SPEC = {
    "member": (int,),
    "kind": (str,),
    "seed": (int,),
    "mode": (str,),
    "passed": (bool,),
    "artifact": (str, type(None)),
    "error": (str, type(None)),
}

#: One nearest-rank distribution block (``metrics.summary_distributions``).
DISTRIBUTION_SPEC = {
    "count": (int,),
    "p50": (int, float, type(None)),
    "p90": (int, float, type(None)),
    "p99": (int, float, type(None)),
    "max": (int, float, type(None)),
}

#: Distribution keys every campaign payload must carry.
CAMPAIGN_DISTRIBUTIONS = ("ticks_to_first_decide", "total_sent",
                          "messages_per_view_change", "decisions")

# --- consensus lineage (schema v12) ---------------------------------------

#: Phase-duration names of one lineage span, in pipeline order
#: (``telemetry.lineage.LINEAGE_DURATIONS``; duplicated literal so this
#: module stays import-light, pinned by ``tests/test_lineage.py``).
#: For every non-truncated span they sum to ``ticks_to_view_change``.
LINEAGE_DURATION_NAMES = ("dissemination_ticks", "cut_fill_ticks",
                          "fast_vote_wait", "fallback_wait",
                          "classic_phase_ticks")

#: Phase-boundary milestone ticks of one lineage span (null == that
#: boundary was not observed in the span's window).
LINEAGE_MILESTONE_NAMES = ("first_alert_tick", "first_report_tick",
                           "announce_tick", "first_vote_tick",
                           "fallback_armed_tick", "phase1a_tick",
                           "phase1b_tick", "phase2a_tick",
                           "phase2b_tick")

#: One per-view-change lineage span (``telemetry.lineage.fold_spans``).
#: ``truncated`` spans carry a decide tick and nothing else — a
#: recorder-ring-evicted window degrades to explicit ignorance, never
#: to wrong ticks.
LINEAGE_SPAN_SPEC = {
    "window_start": _OPT_INT,
    "decide_tick": (int,),
    "ticks_to_view_change": _OPT_INT,
    "fallback": (bool,),
    "truncated": (bool,),
    "milestones": (dict,),
    "durations": (dict,),
    "critical_path": (dict, type(None)),
}

#: Critical-path attribution of a per-receiver span: the last-arriving
#: report/vote edge into the deciding slot, and the index of the
#: ``DelayRule`` covering that edge (null when no rule slowed it).
LINEAGE_CRITICAL_PATH_SPEC = {
    "src": (int,),
    "dst": (int,),
    "send_tick": (int,),
    "arrival_tick": (int,),
    "delay_rule": _OPT_INT,
}

#: A lineage span-population summary
#: (``telemetry.lineage.lineage_summary``): span/fallback/truncated
#: counts plus one DISTRIBUTION_SPEC block per phase duration.
LINEAGE_SUMMARY_SPEC = {
    "spans": (int,),
    "fallbacks": (int,),
    "truncated": (int,),
    "durations": (dict,),
}

#: The required ``campaign.lineage`` block: the fleet-wide summary plus
#: per-scenario-kind and per-delay-regime breakdowns (each value one
#: LINEAGE_SUMMARY_SPEC block).
CAMPAIGN_LINEAGE_SPEC = dict(LINEAGE_SUMMARY_SPEC,
                             by_kind=(dict,), by_regime=(dict,))

#: Per-dispatch stage keys of the campaign dispatch observatory (schema
#: v5), in pipeline order. ``sample``/``lower`` are the host costs
#: attributed to the dispatch's members, ``stack`` the padding+stack of
#: the batched pytree, ``compile`` the one-time AOT lower+compile (0.0
#: on executable-cache hits), ``execute`` the fenced device dispatch,
#: ``fold`` the per-member summary fold.
DISPATCH_STAGES = ("sample", "lower", "stack", "compile", "execute",
                   "fold")

#: One ``dispatch_timeline`` record (schema v5). ``wall_s`` is the sum
#: of the stage walls by construction; ``clusters_per_sec`` is null when
#: the dispatch wall is below the measurable floor. ``host_blocked_frac``
#: is the fraction of the dispatch wall the host spent off-device
#: (everything but ``execute``) — the per-dispatch double-buffering
#: headroom signal.
DISPATCH_RECORD_SPEC = {
    "index": (int,),
    "mode": (str,),
    "pool_id": (int,),
    "pool_shape": (dict,),
    "members": (int,),
    "pad_members": (int,),
    "fleet_size": (int,),
    "kinds": (dict,),
    "compiled": (bool,),
    "stages": (dict,),
    "wall_s": _NUM,
    "clusters_per_sec": (int, float, type(None)),
    "host_blocked_frac": (int, float, type(None)),
    "padding": (dict,),
    "memory": (dict,),
}

#: Padding waste of one dispatch: inert rows added by ``stack_members``
#: / ``stack_receiver_members`` to reach the campaign-global maxima
#: (link-window rows, fallback instance rows, fallback pid rows,
#: provably-inert delay rules), summed over the fleet axis.
DISPATCH_PADDING_SPEC = {
    "window_rows": (int,),
    "fallback_instances": (int,),
    "fallback_pids": (int,),
    "delay_rules": (int,),
}

#: Device-memory watermark after one dispatch. ``live_buffer_bytes``
#: sums ``jax.live_arrays()`` (host-process-wide, so it is a watermark,
#: not an attribution); ``device_peak_bytes`` comes from
#: ``device.memory_stats()`` and is null on backends that expose none
#: (CPU).
DISPATCH_MEMORY_SPEC = {
    "live_buffer_bytes": (int,),
    "device_peak_bytes": _OPT_INT,
}

#: One AOT compile record (``engine.fleet.fleet_aot_compile``): the
#: lower/compile wall split plus XLA's memory analysis of the compiled
#: fleet program.
AOT_COMPILE_SPEC = {
    "lower_s": _NUM,
    "compile_s": _NUM,
    "argument_bytes": (int,),
    "output_bytes": (int,),
    "temp_bytes": (int,),
    "peak_bytes": (int,),
}

#: Top-level ``observatory`` block of a campaign payload (schema v5):
#: where the campaign wall actually went. ``device_busy_s`` is the
#: fenced execute total, ``compile_s`` the one-time AOT cost,
#: ``host_blocked_s`` everything else (sample/lower/stack/fold/glue);
#: ``overlap_headroom_s`` = min(host_blocked_s, device_busy_s) is the
#: wall a perfect double-buffer could hide. ``compile`` carries one
#: AOT_COMPILE_SPEC record per dispatch mode (null when the mode never
#: dispatched).
OBSERVATORY_SPEC = {
    "host_blocked_s": _NUM,
    "device_busy_s": _NUM,
    "compile_s": _NUM,
    "host_blocked_frac": (int, float, type(None)),
    "device_busy_frac": (int, float, type(None)),
    "overlap_headroom_s": _NUM,
    "min_measurable_wall_s": _NUM,
    "compile": (dict,),
    "pipeline": (dict,),
}

#: Dispatch-pipeline block of the observatory (schema v7): whether the
#: double-buffered driver ran, its configured in-flight depth, and the
#: depth it actually reached (``peak_in_flight == 1`` under
#: ``--no-pipeline`` or when the plan has a single dispatch).
PIPELINE_SPEC = {
    "enabled": (bool,),
    "max_in_flight": (int,),
    "peak_in_flight": (int,),
}

#: One ``record: "dispatch"`` heartbeat line of a ``--progress`` JSONL
#: stream (schema v7 adds the pool identity and the live pipeline
#: depth *after* this dispatch retired; schema v8 adds ``anomalies`` —
#: the running per-class anomaly counts over the members retired so
#: far, so a long campaign's heartbeats show trouble as it accumulates,
#: not at the final fold).
PROGRESS_DISPATCH_SPEC = {
    "record": (str,),
    "index": (int,),
    "mode": (str,),
    "pool_id": (int,),
    "pool_shape": (dict,),
    "in_flight_dispatches": (int,),
    "clusters_done": (int,),
    "clusters_total": (int,),
    "stages": (dict,),
    "spot_failures": (int,),
    "anomalies": (dict,),
    # Schema v9: per-dispatch throughput, same null-below-the-floor rate
    # convention as the streaming records.
    "ticks_per_sec": (int, float, type(None)),
    "events_per_sec": (int, float, type(None)),
}

# --- streaming service records (schema v9) --------------------------------

#: The ``TrafficConfig.as_dict()`` block embedded in streaming records.
TRAFFIC_CONFIG_SPEC = {
    "seed": (int,),
    "join_rate_per_ktick": _NUM,
    "leave_burst_rate_per_ktick": _NUM,
    "leave_burst_size": (int,),
    "diurnal_amplitude": _NUM,
    "diurnal_period_ticks": (int,),
    "burst_spacing_ticks": (int,),
    "max_join_burst": (int,),
    "min_members": (int,),
    "reuse_slots": (bool,),
    # Schema v10: closed-loop sampling (one uniform per tick, Poisson by
    # CDF inversion) — the mode the load servo requires.
    "closed_loop": (bool,),
}

#: Per-chunk traffic lowering counts (``TrafficGenerator.next_chunk``
#: info block).
STREAM_TRAFFIC_INFO_SPEC = {
    "bursts": (int,),
    "joins": (int,),
    "leaves": (int,),
    "backlog_joins": (int,),
    "backlog_leaves": (int,),
    "n_members": (int,),
    "events": (int,),
}

#: The checkpoint-proof block of a save/restore round trip
#: (``ResidentEngine.verify_round_trip``). Boolean ``*_identical``
#: fields are the bit-exactness verdicts; the recorder pair is null when
#: the run has no flight recorder.
STREAM_CHECKPOINT_SPEC = {
    "version": (int,),
    "tick": (int,),
    "state_identical": (bool,),
    "recorder_identical": (bool, type(None)),
    "logs_identical": (bool,),
    "final_identical": (bool,),
    "continuation_recorder_identical": (bool, type(None)),
}

#: One ``record: "chunk"`` heartbeat of the resident-engine JSONL
#: stream. ``traffic`` is null when no generator is attached;
#: ``checkpoint`` is non-null only on the chunk that performed a
#: save/restore round trip.
STREAM_CHUNK_SPEC = {
    "record": (str,),
    "index": (int,),
    "tick": (int,),
    "ticks": (int,),
    "wall_s": _NUM,
    # Schema v10: chunk 0 splits the one-time trace+compile wall out of
    # ``wall_s`` (null on every later chunk), so heartbeat rates — and
    # the servo's control input — measure execution, not the compiler.
    "compile_s": (int, float, type(None)),
    "ticks_per_sec": (int, float, type(None)),
    "events_per_sec": (int, float, type(None)),
    "announces": (int,),
    "decides": (int,),
    "live_buffer_bytes": (int,),
    "traffic": (dict, type(None)),
    # Schema v10: null unless a LoadServo / SloWindows is attached.
    "servo": (dict, type(None)),
    "slo": (dict, type(None)),
    # Schema v12: rolling last-window lineage summary (null before the
    # first folded chunk).
    "lineage": (dict, type(None)),
    "checkpoint": (dict, type(None)),
}

#: Live-buffer watermark block of the stream summary. ``steady_max``
#: excludes checkpoint-verify chunks (those transiently hold the live
#: and restored branches side by side) — the flat-memory soak gate
#: compares it against ``first``.
STREAM_WATERMARK_SPEC = {
    "first": _OPT_INT,
    "max": _OPT_INT,
    "steady_max": _OPT_INT,
    "last": _OPT_INT,
}

#: The final ``record: "stream_summary"`` line of a resident run (also
#: the ``summary`` block of a committed soak artifact).
STREAM_SUMMARY_SPEC = {
    "record": (str,),
    "schema_version": (int,),
    "source": (str,),
    "n": (int,),
    "capacity": (int,),
    "ticks": (int,),
    "chunks": (int,),
    "chunk_ticks": (int,),
    "events_injected": (int,),
    "joins": (int,),
    "leaves": (int,),
    "bursts": (int,),
    "announcements": (int,),
    "decisions": (int,),
    "wall_s": _NUM,
    "compile_s": (int, float, type(None)),
    "ticks_per_sec": (int, float, type(None)),
    "events_per_sec": (int, float, type(None)),
    "ticks_to_view_change": (dict,),
    "live_buffer_bytes": (dict,),
    "traffic": (dict, type(None)),
    # Schema v10: the final servo state ({"config", "final"}) and the
    # final rolling SLO window; null when not attached.
    "servo": (dict, type(None)),
    "slo": (dict, type(None)),
    # Schema v12: whole-run lineage summary (null when the run folded
    # no lineage).
    "lineage": (dict, type(None)),
    "checkpoint": (dict, type(None)),
}

# --- streaming observatory records (schema v10) ---------------------------

#: ``service.servo.ServoConfig.as_dict()`` — the control-law constants
#: a committed sweep is exactly reproducible from.
SERVO_CONFIG_SPEC = {
    "target_events_per_sec": _NUM,
    "initial_ticks_per_sec": _NUM,
    "pinned_ticks_per_sec": (int, float, type(None)),
    "gain": _NUM,
    "rate_quantum_per_ktick": _NUM,
    "min_rate_per_ktick": _NUM,
    "max_rate_per_ktick": _NUM,
    "tps_quantum": _NUM,
}

#: The per-chunk ``servo`` heartbeat block
#: (``LoadServo.chunk_block``): ``rate_per_ktick`` is the quantized
#: rate the chunk actually ran at, ``backlog`` the generator's
#: offered-minus-applied saturation observable.
SERVO_CHUNK_SPEC = {
    "target_events_per_sec": _NUM,
    "rate_per_ktick": _NUM,
    "ticks_per_sec_estimate": _NUM,
    "backlog": (int,),
    "updates": (int,),
}

#: The metric names every ``slo`` block carries
#: (``telemetry.slo.SLO_METRICS``, duplicated here so this module stays
#: dependency-free).
SLO_METRIC_NAMES = ("decide_latency", "ticks_to_view_change")

#: One windowed metric: bucket counts over the window plus nearest-rank
#: percentiles as bucket upper edges (null when the window is empty).
SLO_METRIC_SPEC = {
    "count": (int,),
    "counts": (list,),
    "p50": _OPT_INT,
    "p95": _OPT_INT,
    "p99": _OPT_INT,
}

#: The rolling ``slo`` heartbeat block (``telemetry.slo.SloWindows``).
SLO_WINDOW_SPEC = {
    "window_chunks": (int,),
    "chunks": (int,),
    "bucket_edges": (list,),
    "metrics": (dict,),
}

#: One ``record: "status_snapshot"`` line of the live status API
#: (``service.status``) — the latest chunk-boundary picture, built
#: purely from already-drained host data.
STATUS_SNAPSHOT_SPEC = {
    "record": (str,),
    "schema_version": (int,),
    "source": (str,),
    "tick": (int,),
    "chunks": (int,),
    "epoch": (int,),
    "n_members": (int,),
    "ticks_per_sec": (int, float, type(None)),
    "events_per_sec": (int, float, type(None)),
    "backlog": (int, type(None)),
    "live_buffer_bytes": (int,),
    "servo": (dict, type(None)),
    "slo": (dict, type(None)),
    # Schema v12: the last chunk's rolling lineage summary.
    "lineage": (dict, type(None)),
    "checkpoint": (dict, type(None)),
    "wall_s": _NUM,
}

#: One target of a ``record: "load_sweep"`` saturation sweep: the servo
#: config it ran under, what it achieved, and the stability verdict
#: (bounded backlog slope over the measured chunks).
LOAD_SWEEP_RATE_SPEC = {
    "target_events_per_sec": _NUM,
    "achieved_events_per_sec": (int, float, type(None)),
    "rate_per_ktick": _NUM,
    "ticks_per_sec": (int, float, type(None)),
    "chunks": (int,),
    "events": (int,),
    "backlog_final": (int,),
    "backlog_slope_per_chunk": _NUM,
    "stable": (bool,),
    "servo_config": (dict,),
    "slo": (dict,),
}

#: The measured knee: the largest stable target (null when every
#: target was unstable), with its achieved rate and windowed tail.
LOAD_SWEEP_KNEE_SPEC = {
    "target_events_per_sec": (int, float, type(None)),
    "achieved_events_per_sec": (int, float, type(None)),
    "ticks_to_view_change_p99": _OPT_INT,
}

#: The ``record: "load_sweep"`` artifact (``benchmarks/load_sweep.json``,
#: ``python -m rapid_tpu.service --load-sweep``).
LOAD_SWEEP_SPEC = {
    "record": (str,),
    "schema_version": (int,),
    "n": (int,),
    "capacity": (int,),
    "chunk_ticks": (int,),
    "chunks_per_rate": (int,),
    "warmup_chunks": (int,),
    "seed": (int,),
    "backlog_slope_threshold": _NUM,
    "targets": (list,),
    "rates": (list,),
    "knee": (dict, type(None)),
    "wall_s": _NUM,
}

#: ``service.checkpoint`` manifest (``manifest.json`` inside a
#: checkpoint directory). ``checkpoint_version`` is the restore
#: compatibility pin — distinct from the telemetry ``schema_version``
#: the manifest also stamps.
CHECKPOINT_MANIFEST_SPEC = {
    "record": (str,),
    "checkpoint_version": (int,),
    "schema_version": (int,),
    "family": (str,),
    "tick": (int,),
    "statics": (dict,),
    "leaves": (list,),
    "host": (dict, type(None)),
}

CHECKPOINT_LEAF_SPEC = {
    "name": (str,),
    "dtype": (str,),
    "shape": (list,),
}

#: Relative slack allowed between a campaign payload's ``wall_s`` and
#: the sum of its per-dispatch stage walls (timer granularity + loop
#: glue); only enforced once the wall is comfortably measurable. The
#: floor sits at a quarter second: with memoized boot state (schema v7)
#: a micro-campaign's true stage work is a few tens of milliseconds, so
#: below this floor driver glue — not instrumentation drift — dominates
#: the residual.
STAGE_SUM_TOLERANCE = 0.10
_STAGE_SUM_MIN_WALL_S = 0.25


def _check(obj: Dict, spec: Dict, where: str) -> List[str]:
    errors = []
    if not isinstance(obj, dict):
        return [f"{where}: expected an object, got {type(obj).__name__}"]
    for key, types in spec.items():
        if key not in obj:
            errors.append(f"{where}.{key}: missing")
        elif not isinstance(obj[key], types) or (
                isinstance(obj[key], bool) and bool not in types):
            errors.append(
                f"{where}.{key}: expected "
                f"{'/'.join(t.__name__ for t in types)}, "
                f"got {type(obj[key]).__name__}")
    return errors


def validate_telemetry(block, where: str = "telemetry") -> List[str]:
    errors = _check(block, TELEMETRY_SPEC, where)
    if isinstance(block, dict):
        for i, vc in enumerate(block.get("view_changes") or []):
            errors += _check(vc, VIEW_CHANGE_SPEC,
                             f"{where}.view_changes[{i}]")
        px = block.get("fallback_phase_sent")
        if isinstance(px, dict):
            errors += _check(
                px, {phase: (int,) for phase in FALLBACK_PHASES},
                f"{where}.fallback_phase_sent")
    return errors


def validate_flight_recorder(block, where: str = "recorder") -> List[str]:
    """Validate one extracted flight-recorder ring payload."""
    errors = _check(block, FLIGHT_RECORDER_SPEC, where)
    if not isinstance(block, dict):
        return errors
    gauges = block.get("gauges")
    n_gauges = len(gauges) if isinstance(gauges, list) else None
    rows = block.get("rows")
    if isinstance(rows, list):
        window = block.get("window")
        if isinstance(window, int) and not isinstance(window, bool) \
                and len(rows) > window:
            errors.append(f"{where}.rows: {len(rows)} rows exceed "
                          f"window={window}")
        for i, row in enumerate(rows):
            if not isinstance(row, list):
                errors.append(f"{where}.rows[{i}]: expected list, "
                              f"got {type(row).__name__}")
            elif n_gauges is not None and len(row) != n_gauges:
                errors.append(f"{where}.rows[{i}]: {len(row)} values for "
                              f"{n_gauges} gauges")
    stamps = block.get("stamps")
    if isinstance(stamps, dict):
        errors += _check(stamps, {s: (int,) for s in RECORDER_STAMPS},
                         f"{where}.stamps")
    return errors


def validate_lineage_span(span, where: str = "lineage_span") -> List[str]:
    """Validate one per-view-change lineage span (schema v12)."""
    errors = _check(span, LINEAGE_SPAN_SPEC, where)
    if not isinstance(span, dict):
        return errors
    if isinstance(span.get("milestones"), dict):
        errors += _check(span["milestones"],
                         {name: _OPT_INT for name in
                          LINEAGE_MILESTONE_NAMES},
                         f"{where}.milestones")
    if isinstance(span.get("durations"), dict):
        errors += _check(span["durations"],
                         {name: _OPT_INT for name in
                          LINEAGE_DURATION_NAMES},
                         f"{where}.durations")
    if isinstance(span.get("critical_path"), dict):
        errors += _check(span["critical_path"], LINEAGE_CRITICAL_PATH_SPEC,
                         f"{where}.critical_path")
    return errors


def validate_lineage_summary(block, where: str = "lineage") -> List[str]:
    """Validate one lineage span-population summary (schema v12): every
    phase duration must carry a distribution block, even when empty."""
    errors = _check(block, LINEAGE_SUMMARY_SPEC, where)
    if not isinstance(block, dict):
        return errors
    durs = block.get("durations")
    if isinstance(durs, dict):
        for name in LINEAGE_DURATION_NAMES:
            if name not in durs:
                errors.append(f"{where}.durations.{name}: missing")
        for name, dist in durs.items():
            if name not in LINEAGE_DURATION_NAMES:
                errors.append(f"{where}.durations.{name}: unknown "
                              f"duration (expected one of "
                              f"{'/'.join(LINEAGE_DURATION_NAMES)})")
            errors += _check(dist, DISTRIBUTION_SPEC,
                             f"{where}.durations.{name}")
    return errors


def validate_campaign_lineage(block, where: str = "campaign.lineage"
                              ) -> List[str]:
    """Validate the required ``campaign.lineage`` block: the fleet-wide
    summary plus ``by_kind``/``by_regime`` breakdown summaries."""
    errors = validate_lineage_summary(block, where)
    if not isinstance(block, dict):
        return errors
    errors += _check(block, {"by_kind": (dict,), "by_regime": (dict,)},
                     where)
    for group in ("by_kind", "by_regime"):
        sub = block.get(group)
        if not isinstance(sub, dict):
            continue
        for key, summary in sub.items():
            errors += validate_lineage_summary(summary,
                                               f"{where}.{group}.{key}")
        if group == "by_regime":
            for key in sub:
                if key not in DELAY_REGIMES:
                    errors.append(f"{where}.by_regime.{key}: unknown "
                                  f"regime (expected one of "
                                  f"{'/'.join(DELAY_REGIMES)})")
    return errors


def validate_triage(block, where: str = "triage") -> List[str]:
    """Validate a campaign ``triage`` block (schema v8)."""
    errors = _check(block, TRIAGE_SPEC, where)
    if not isinstance(block, dict):
        return errors
    classes = block.get("classes")
    if not isinstance(classes, dict):
        return errors
    for name in TRIAGE_CLASSES:
        if name not in classes:
            errors.append(f"{where}.classes.{name}: missing")
    for name, cls in classes.items():
        cw = f"{where}.classes.{name}"
        if name not in TRIAGE_CLASSES:
            errors.append(f"{cw}: unknown class (expected one of "
                          f"{'/'.join(TRIAGE_CLASSES)})")
        errors += _check(cls, TRIAGE_CLASS_SPEC, cw)
        if not isinstance(cls, dict):
            continue
        for i, ex in enumerate(cls.get("exemplars") or []):
            ew = f"{cw}.exemplars[{i}]"
            errors += _check(ex, TRIAGE_EXEMPLAR_SPEC, ew)
            if not isinstance(ex, dict):
                continue
            if isinstance(ex.get("expected"), dict):
                errors += _check(ex["expected"], TRIAGE_EXPECTED_SPEC,
                                 f"{ew}.expected")
            if isinstance(ex.get("recorder"), dict):
                errors += validate_flight_recorder(ex["recorder"],
                                                   f"{ew}.recorder")
            if isinstance(ex.get("lineage"), list):
                for j, span in enumerate(ex["lineage"]):
                    errors += validate_lineage_span(
                        span, f"{ew}.lineage[{j}]")
    return errors


def validate_tournament(block, where: str = "tournament") -> List[str]:
    """Validate one ``campaign.tournament`` A/B block (schema v11)."""
    errors = _check(block, TOURNAMENT_SPEC, where)
    if not isinstance(block, dict):
        return errors
    raw = block.get("variants")
    names = [v for v in raw if isinstance(v, str)] \
        if isinstance(raw, list) else []
    for v in names:
        if v not in PROTOCOL_VARIANTS:
            errors.append(f"{where}.variants: {v!r} is not one of "
                          f"{'/'.join(PROTOCOL_VARIANTS)}")
    per = block.get("per_variant")
    if isinstance(per, dict):
        for v in names:
            if v not in per:
                errors.append(f"{where}.per_variant.{v}: missing")
        for v, row in per.items():
            vw = f"{where}.per_variant.{v}"
            if v not in names:
                errors.append(f"{vw}: names no tournament variant")
            errors += _check(row, TOURNAMENT_VARIANT_SPEC, vw)
            if isinstance(row, dict) \
                    and isinstance(row.get("decide_ticks"), dict):
                errors += _check(row["decide_ticks"], DISTRIBUTION_SPEC,
                                 f"{vw}.decide_ticks")
            if isinstance(row, dict) \
                    and isinstance(row.get("lineage"), dict):
                errors += validate_lineage_summary(row["lineage"],
                                                   f"{vw}.lineage")
    wl = block.get("win_loss")
    if isinstance(wl, dict):
        for kind, row in wl.items():
            kw = f"{where}.win_loss.{kind}"
            if not isinstance(row, dict):
                errors.append(f"{kw}: expected an object, "
                              f"got {type(row).__name__}")
                continue
            for key in names + ["tie"]:
                if key not in row:
                    errors.append(f"{kw}.{key}: missing")
            for key, count in row.items():
                if not isinstance(count, int) or isinstance(count, bool):
                    errors.append(f"{kw}.{key}: expected int, "
                                  f"got {type(count).__name__}")
    return errors


def validate_campaign(block, where: str = "campaign") -> List[str]:
    errors = _check(block, CAMPAIGN_SPEC, where)
    if not isinstance(block, dict):
        return errors
    pv = block.get("protocol_variant")
    if isinstance(pv, str) and pv not in PROTOCOL_VARIANTS:
        errors.append(f"{where}.protocol_variant: {pv!r} is not one of "
                      f"{'/'.join(PROTOCOL_VARIANTS)}")
    if "tournament" in block:
        errors += validate_tournament(block["tournament"],
                                      f"{where}.tournament")
    kinds = block.get("scenario_kinds")
    if isinstance(kinds, dict):
        for kind, count in kinds.items():
            if not isinstance(count, int) or isinstance(count, bool):
                errors.append(f"{where}.scenario_kinds.{kind}: expected "
                              f"int, got {type(count).__name__}")
    pools = block.get("pools")
    if isinstance(pools, list):
        for i, pool in enumerate(pools):
            pw = f"{where}.pools[{i}]"
            errors += _check(pool, CAMPAIGN_POOL_SPEC, pw)
            if not isinstance(pool, dict):
                continue
            if isinstance(pool.get("pool_id"), int) \
                    and pool["pool_id"] != i:
                errors.append(f"{pw}.pool_id: expected {i}, "
                              f"got {pool['pool_id']}")
            if pool.get("mode") not in ("shared", "per_receiver", None):
                errors.append(f"{pw}.mode: expected 'shared' or "
                              f"'per_receiver', got {pool['mode']!r}")
            if isinstance(pool.get("shape"), dict):
                errors += _check(pool["shape"], DISPATCH_PADDING_SPEC,
                                 f"{pw}.shape")
    if isinstance(block.get("per_receiver"), dict):
        errors += _check(block["per_receiver"], PER_RECEIVER_SPEC,
                         f"{where}.per_receiver")
    if isinstance(block.get("spot_checks"), dict):
        errors += _check(block["spot_checks"], SPOT_CHECK_SPEC,
                         f"{where}.spot_checks")
        for i, m in enumerate(block["spot_checks"].get("members") or []):
            errors += _check(m, SPOT_MEMBER_SPEC,
                             f"{where}.spot_checks.members[{i}]")
    dists = block.get("distributions")
    if isinstance(dists, dict):
        for key in CAMPAIGN_DISTRIBUTIONS:
            if key not in dists:
                errors.append(f"{where}.distributions.{key}: missing")
            else:
                errors += _check(dists[key], DISTRIBUTION_SPEC,
                                 f"{where}.distributions.{key}")
    regimes = block.get("delay_regimes")
    if isinstance(regimes, dict):
        for key, dist in regimes.items():
            if key not in DELAY_REGIMES:
                errors.append(f"{where}.delay_regimes.{key}: unknown "
                              f"regime (expected one of "
                              f"{'/'.join(DELAY_REGIMES)})")
            errors += _check(dist, DISTRIBUTION_SPEC,
                             f"{where}.delay_regimes.{key}")
    if "triage" in block:
        errors += validate_triage(block["triage"], f"{where}.triage")
    if isinstance(block.get("lineage"), dict):
        errors += validate_campaign_lineage(block["lineage"],
                                            f"{where}.lineage")
    return errors


def validate_dispatch_timeline(timeline, where: str = "dispatch_timeline"
                               ) -> List[str]:
    """Validate one campaign's per-dispatch timeline (schema v5)."""
    errors: List[str] = []
    if not isinstance(timeline, list):
        return [f"{where}: expected a list, "
                f"got {type(timeline).__name__}"]
    for i, rec in enumerate(timeline):
        rw = f"{where}[{i}]"
        errors += _check(rec, DISPATCH_RECORD_SPEC, rw)
        if not isinstance(rec, dict):
            continue
        if isinstance(rec.get("index"), int) and rec["index"] != i:
            errors.append(f"{rw}.index: expected {i}, got {rec['index']}")
        if rec.get("mode") not in ("shared", "per_receiver", None):
            errors.append(f"{rw}.mode: expected 'shared' or "
                          f"'per_receiver', got {rec['mode']!r}")
        stages = rec.get("stages")
        if isinstance(stages, dict):
            errors += _check(stages,
                             {s: _NUM for s in DISPATCH_STAGES},
                             f"{rw}.stages")
            extra = set(stages) - set(DISPATCH_STAGES)
            for s in sorted(extra):
                errors.append(f"{rw}.stages.{s}: unknown stage")
        if isinstance(rec.get("padding"), dict):
            errors += _check(rec["padding"], DISPATCH_PADDING_SPEC,
                             f"{rw}.padding")
        if isinstance(rec.get("pool_shape"), dict):
            errors += _check(rec["pool_shape"], DISPATCH_PADDING_SPEC,
                             f"{rw}.pool_shape")
        if isinstance(rec.get("memory"), dict):
            errors += _check(rec["memory"], DISPATCH_MEMORY_SPEC,
                             f"{rw}.memory")
    return errors


def validate_observatory(block, where: str = "observatory") -> List[str]:
    errors = _check(block, OBSERVATORY_SPEC, where)
    if not isinstance(block, dict):
        return errors
    compile_block = block.get("compile")
    if isinstance(compile_block, dict):
        for mode in ("shared", "per_receiver"):
            if mode not in compile_block:
                errors.append(f"{where}.compile.{mode}: missing")
                continue
            entry = compile_block[mode]
            if entry is not None:  # null == that mode never dispatched
                errors += _check(entry, AOT_COMPILE_SPEC,
                                 f"{where}.compile.{mode}")
        # Schema v7: the per-pool compile ledger — one record per
        # (mode, shape-bucket) executable the campaign actually built.
        pools = compile_block.get("pools")
        if pools is None:
            errors.append(f"{where}.compile.pools: missing")
        elif not isinstance(pools, list):
            errors.append(f"{where}.compile.pools: expected list, "
                          f"got {type(pools).__name__}")
        else:
            for i, entry in enumerate(pools):
                errors += _check(entry, dict(AOT_COMPILE_SPEC,
                                             pool_id=(int,), mode=(str,)),
                                 f"{where}.compile.pools[{i}]")
    pipeline = block.get("pipeline")
    if isinstance(pipeline, dict):
        errors += _check(pipeline, PIPELINE_SPEC, f"{where}.pipeline")
    return errors


def validate_progress_stream(lines, where: str = "progress") -> List[str]:
    """Validate the ``record: "dispatch"`` lines of a ``--progress``
    JSONL heartbeat stream (schema v7). Non-dispatch records (campaign
    summary, spot checks) pass through unchecked — their shapes belong
    to their own producers."""
    errors: List[str] = []
    saw_dispatch = False
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            errors.append(f"{where}[{i}]: not JSON ({e})")
            continue
        if not isinstance(rec, dict) or rec.get("record") != "dispatch":
            continue
        saw_dispatch = True
        rw = f"{where}[{i}]"
        errors += _check(rec, PROGRESS_DISPATCH_SPEC, rw)
        if isinstance(rec.get("pool_shape"), dict):
            errors += _check(rec["pool_shape"], DISPATCH_PADDING_SPEC,
                             f"{rw}.pool_shape")
        if isinstance(rec.get("stages"), dict):
            errors += _check(rec["stages"],
                             {s: _NUM for s in DISPATCH_STAGES},
                             f"{rw}.stages")
        anomalies = rec.get("anomalies")
        if isinstance(anomalies, dict):
            for name, count in anomalies.items():
                if name not in TRIAGE_CLASSES:
                    errors.append(f"{rw}.anomalies.{name}: unknown "
                                  f"triage class")
                if not isinstance(count, int) or isinstance(count, bool):
                    errors.append(f"{rw}.anomalies.{name}: expected int, "
                                  f"got {type(count).__name__}")
    if not saw_dispatch:
        errors.append(f"{where}: no dispatch heartbeat records")
    return errors


def validate_slo_window(block, where: str = "slo") -> List[str]:
    """Validate one rolling ``slo`` window block (schema v10): both
    metrics present, each count vector exactly one bucket per edge and
    summing to its ``count``."""
    errors = _check(block, SLO_WINDOW_SPEC, where)
    if not isinstance(block, dict):
        return errors
    edges = block.get("bucket_edges")
    n_edges = len(edges) if isinstance(edges, list) else None
    metrics = block.get("metrics")
    if not isinstance(metrics, dict):
        return errors
    for name in SLO_METRIC_NAMES:
        if name not in metrics:
            errors.append(f"{where}.metrics.{name}: missing")
    for name, metric in metrics.items():
        mw = f"{where}.metrics.{name}"
        if name not in SLO_METRIC_NAMES:
            errors.append(f"{mw}: unknown metric (expected one of "
                          f"{'/'.join(SLO_METRIC_NAMES)})")
        errors += _check(metric, SLO_METRIC_SPEC, mw)
        if not isinstance(metric, dict):
            continue
        counts = metric.get("counts")
        if isinstance(counts, list):
            if n_edges is not None and len(counts) != n_edges:
                errors.append(f"{mw}.counts: {len(counts)} buckets for "
                              f"{n_edges} edges")
            total = sum(c for c in counts
                        if isinstance(c, int) and not isinstance(c, bool))
            if isinstance(metric.get("count"), int) \
                    and metric["count"] != total:
                errors.append(f"{mw}.count: {metric['count']} != "
                              f"sum(counts) = {total}")
    return errors


def validate_servo_summary(block, where: str = "servo") -> List[str]:
    """Validate a summary ``servo`` block ({"config", "final"})."""
    errors: List[str] = []
    if not isinstance(block, dict):
        return [f"{where}: expected an object, got {type(block).__name__}"]
    errors += _check(block.get("config"), SERVO_CONFIG_SPEC,
                     f"{where}.config")
    errors += _check(block.get("final"), SERVO_CHUNK_SPEC,
                     f"{where}.final")
    return errors


def validate_stream_chunk(rec, where: str = "chunk") -> List[str]:
    """Validate one ``record: "chunk"`` resident heartbeat."""
    errors = _check(rec, STREAM_CHUNK_SPEC, where)
    if not isinstance(rec, dict):
        return errors
    if isinstance(rec.get("traffic"), dict):
        errors += _check(rec["traffic"], STREAM_TRAFFIC_INFO_SPEC,
                         f"{where}.traffic")
    if isinstance(rec.get("servo"), dict):
        errors += _check(rec["servo"], SERVO_CHUNK_SPEC, f"{where}.servo")
    if isinstance(rec.get("slo"), dict):
        errors += validate_slo_window(rec["slo"], f"{where}.slo")
    if isinstance(rec.get("lineage"), dict):
        errors += validate_lineage_summary(rec["lineage"],
                                           f"{where}.lineage")
    if isinstance(rec.get("checkpoint"), dict):
        errors += _check(rec["checkpoint"], STREAM_CHECKPOINT_SPEC,
                         f"{where}.checkpoint")
    return errors


def validate_stream_summary(rec, where: str = "stream_summary"
                            ) -> List[str]:
    """Validate the final ``record: "stream_summary"`` line."""
    errors = _check(rec, STREAM_SUMMARY_SPEC, where)
    if not isinstance(rec, dict):
        return errors
    errors += _version_errors(rec)
    if isinstance(rec.get("ticks_to_view_change"), dict):
        errors += _check(rec["ticks_to_view_change"], DISTRIBUTION_SPEC,
                         f"{where}.ticks_to_view_change")
    if isinstance(rec.get("live_buffer_bytes"), dict):
        errors += _check(rec["live_buffer_bytes"], STREAM_WATERMARK_SPEC,
                         f"{where}.live_buffer_bytes")
    if isinstance(rec.get("traffic"), dict):
        errors += _check(rec["traffic"], TRAFFIC_CONFIG_SPEC,
                         f"{where}.traffic")
    if isinstance(rec.get("servo"), dict):
        errors += validate_servo_summary(rec["servo"], f"{where}.servo")
    if isinstance(rec.get("slo"), dict):
        errors += validate_slo_window(rec["slo"], f"{where}.slo")
    if isinstance(rec.get("lineage"), dict):
        errors += validate_lineage_summary(rec["lineage"],
                                           f"{where}.lineage")
    if isinstance(rec.get("checkpoint"), dict):
        errors += _check(rec["checkpoint"], STREAM_CHECKPOINT_SPEC,
                         f"{where}.checkpoint")
    return errors


def validate_status_snapshot(rec, where: str = "status") -> List[str]:
    """Validate one live ``record: "status_snapshot"`` line (schema
    v10) — the status file's content and every socket reply."""
    errors = _check(rec, STATUS_SNAPSHOT_SPEC, where)
    if not isinstance(rec, dict):
        return errors
    errors += _version_errors(rec)
    if rec.get("record") != "status_snapshot":
        errors.append(f"{where}.record: expected 'status_snapshot', "
                      f"got {rec.get('record')!r}")
    if isinstance(rec.get("servo"), dict):
        errors += _check(rec["servo"], SERVO_CHUNK_SPEC, f"{where}.servo")
    if isinstance(rec.get("slo"), dict):
        errors += validate_slo_window(rec["slo"], f"{where}.slo")
    if isinstance(rec.get("lineage"), dict):
        errors += validate_lineage_summary(rec["lineage"],
                                           f"{where}.lineage")
    if isinstance(rec.get("checkpoint"), dict):
        errors += _check(rec["checkpoint"], STREAM_CHECKPOINT_SPEC,
                         f"{where}.checkpoint")
    return errors


def validate_load_sweep(payload, where: str = "load_sweep") -> List[str]:
    """Validate a ``record: "load_sweep"`` saturation-sweep artifact:
    one rate entry per target (in target order), each with a schema-
    valid servo config and SLO window, and a knee consistent with the
    stability verdicts (the largest stable target, or null)."""
    errors = _check(payload, LOAD_SWEEP_SPEC, where)
    if not isinstance(payload, dict):
        return errors
    errors += _version_errors(payload)
    if payload.get("record") != "load_sweep":
        errors.append(f"{where}.record: expected 'load_sweep', "
                      f"got {payload.get('record')!r}")
    targets = payload.get("targets")
    rates = payload.get("rates")
    if isinstance(targets, list) and isinstance(rates, list) \
            and len(targets) != len(rates):
        errors.append(f"{where}.rates: {len(rates)} entries for "
                      f"{len(targets)} targets")
    best_stable = None
    if isinstance(rates, list):
        for i, rate in enumerate(rates):
            rw = f"{where}.rates[{i}]"
            errors += _check(rate, LOAD_SWEEP_RATE_SPEC, rw)
            if not isinstance(rate, dict):
                continue
            if isinstance(targets, list) and i < len(targets) \
                    and rate.get("target_events_per_sec") != targets[i]:
                errors.append(
                    f"{rw}.target_events_per_sec: expected "
                    f"{targets[i]!r}, got "
                    f"{rate.get('target_events_per_sec')!r}")
            if isinstance(rate.get("servo_config"), dict):
                errors += _check(rate["servo_config"], SERVO_CONFIG_SPEC,
                                 f"{rw}.servo_config")
            if isinstance(rate.get("slo"), dict):
                errors += validate_slo_window(rate["slo"], f"{rw}.slo")
            if rate.get("stable") is True and isinstance(
                    rate.get("target_events_per_sec"), (int, float)):
                t = rate["target_events_per_sec"]
                if best_stable is None or t > best_stable:
                    best_stable = t
    knee = payload.get("knee")
    if isinstance(knee, dict):
        errors += _check(knee, LOAD_SWEEP_KNEE_SPEC, f"{where}.knee")
        if knee.get("target_events_per_sec") != best_stable:
            errors.append(
                f"{where}.knee.target_events_per_sec: expected the "
                f"largest stable target ({best_stable!r}), got "
                f"{knee.get('target_events_per_sec')!r}")
    elif knee is None and best_stable is not None:
        errors.append(f"{where}.knee: null despite stable targets "
                      f"(largest: {best_stable!r})")
    return errors


def validate_checkpoint_manifest(manifest, where: str = "manifest"
                                 ) -> List[str]:
    """Validate a ``service.checkpoint`` ``manifest.json`` payload
    (structure only — version/statics *compatibility* is the loader's
    job and raises typed errors there)."""
    errors = _check(manifest, CHECKPOINT_MANIFEST_SPEC, where)
    if not isinstance(manifest, dict):
        return errors
    for i, leaf in enumerate(manifest.get("leaves") or []):
        errors += _check(leaf, CHECKPOINT_LEAF_SPEC, f"{where}.leaves[{i}]")
    return errors


def validate_streaming_stream(lines, where: str = "stream") -> List[str]:
    """Validate a resident-engine JSONL metrics stream: every
    ``record: "chunk"`` heartbeat, exactly one trailing
    ``record: "stream_summary"`` line. Tick rows (no ``record`` key)
    pass through unchecked — their shape is ``TickMetrics.as_dict`` and
    belongs to the metrics producer."""
    errors: List[str] = []
    chunks = 0
    summaries = 0
    last_kind = None
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            errors.append(f"{where}[{i}]: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{where}[{i}]: expected an object")
            continue
        kind = rec.get("record")
        last_kind = kind
        if kind == "chunk":
            chunks += 1
            errors += validate_stream_chunk(rec, f"{where}[{i}]")
        elif kind == "stream_summary":
            summaries += 1
            errors += validate_stream_summary(rec, f"{where}[{i}]")
    if chunks == 0:
        errors.append(f"{where}: no chunk heartbeat records")
    if summaries != 1:
        errors.append(f"{where}: expected exactly one stream_summary "
                      f"record, found {summaries}")
    elif last_kind != "stream_summary":
        errors.append(f"{where}: stream_summary must be the final record")
    return errors


#: Extra required fields of the ``scenario: "streaming"`` bench run
#: (schema v9) on top of ``RUN_SPEC``.
STREAMING_RUN_SPEC = {
    "scenario": (str,),
    "capacity": (int,),
    "chunk_ticks": (int,),
    "chunks": (int,),
    "events_injected": (int,),
    "events_per_sec": (int, float, type(None)),
    "traffic": (dict,),
    "ticks_to_view_change": (dict,),
    # Schema v12: whole-run lineage summary.
    "lineage": (dict, type(None)),
    "checkpoint": (dict, type(None)),
}


def validate_run_payload(payload, where: str = "payload") -> List[str]:
    errors = _check(payload, RUN_SPEC, where)
    if isinstance(payload, dict) and isinstance(payload.get("telemetry"),
                                                dict):
        errors += validate_telemetry(payload["telemetry"],
                                     f"{where}.telemetry")
    if isinstance(payload, dict) and payload.get("scenario") == "streaming":
        errors += _check(payload, STREAMING_RUN_SPEC, where)
        if isinstance(payload.get("traffic"), dict):
            errors += _check(payload["traffic"], TRAFFIC_CONFIG_SPEC,
                             f"{where}.traffic")
        if isinstance(payload.get("ticks_to_view_change"), dict):
            errors += _check(payload["ticks_to_view_change"],
                             DISTRIBUTION_SPEC,
                             f"{where}.ticks_to_view_change")
        if isinstance(payload.get("lineage"), dict):
            errors += validate_lineage_summary(payload["lineage"],
                                               f"{where}.lineage")
        if isinstance(payload.get("checkpoint"), dict):
            errors += _check(payload["checkpoint"], STREAM_CHECKPOINT_SPEC,
                             f"{where}.checkpoint")
    if isinstance(payload, dict) and "campaign" in payload:
        errors += validate_campaign(payload["campaign"], f"{where}.campaign")
        # Schema v5: a campaign payload must carry the dispatch
        # observatory — the per-dispatch timeline, the host/device wall
        # accounting, and the fleet throughput figure.
        for key, types in (("dispatch_timeline", (list,)),
                           ("observatory", (dict,)),
                           ("clusters_per_sec", (int, float, type(None)))):
            if key not in payload:
                errors.append(f"{where}.{key}: missing")
            elif not isinstance(payload[key], types):
                errors.append(f"{where}.{key}: expected "
                              f"{'/'.join(t.__name__ for t in types)}, "
                              f"got {type(payload[key]).__name__}")
        errors += validate_dispatch_timeline(
            payload.get("dispatch_timeline") or [],
            f"{where}.dispatch_timeline")
        if isinstance(payload.get("observatory"), dict):
            errors += validate_observatory(payload["observatory"],
                                           f"{where}.observatory")
        # Semantic cross-check: the per-stage walls must account for the
        # campaign wall (within tolerance) — a timeline that doesn't sum
        # to the wall it claims to explain is instrumentation drift.
        wall = payload.get("wall_s")
        timeline = payload.get("dispatch_timeline")
        if isinstance(wall, (int, float)) and not isinstance(wall, bool) \
                and isinstance(timeline, list) \
                and wall >= _STAGE_SUM_MIN_WALL_S:
            stage_sum = 0.0
            for rec in timeline:
                if isinstance(rec, dict) and isinstance(rec.get("stages"),
                                                        dict):
                    stage_sum += sum(
                        v for v in rec["stages"].values()
                        if isinstance(v, (int, float))
                        and not isinstance(v, bool))
            if abs(wall - stage_sum) > STAGE_SUM_TOLERANCE * wall:
                errors.append(
                    f"{where}.dispatch_timeline: stage walls sum to "
                    f"{stage_sum:.3f}s, outside ±"
                    f"{STAGE_SUM_TOLERANCE * 100:.0f}% of wall_s={wall:.3f}s")
    return errors


def validate_profile_payload(payload, where: str = "payload") -> List[str]:
    """Validate a ``kernel_profile_sweep`` dominance report."""
    errors = _check(payload, PROFILE_SWEEP_SPEC, where)
    if not isinstance(payload, dict):
        return errors
    for i, run in enumerate(payload.get("runs") or []):
        rw = f"{where}.runs[{i}]"
        errors += _check(run, PROFILE_RUN_SPEC, rw)
        if not isinstance(run, dict):
            continue
        names = set()
        for j, kc in enumerate(run.get("kernels") or []):
            errors += _check(kc, KERNEL_COST_SPEC, f"{rw}.kernels[{j}]")
            if isinstance(kc, dict) and isinstance(kc.get("kernel"), str):
                names.add(kc["kernel"])
        dom = run.get("dominant")
        if isinstance(dom, dict):
            for axis, kernel in dom.items():
                if kernel not in names:
                    errors.append(f"{rw}.dominant.{axis}: {kernel!r} "
                                  f"names no profiled kernel")
    dom_by_n = payload.get("dominant_by_n")
    if isinstance(dom_by_n, dict):
        for n, kernel in dom_by_n.items():
            if not isinstance(kernel, str):
                errors.append(f"{where}.dominant_by_n[{n}]: expected str, "
                              f"got {type(kernel).__name__}")
    mc = payload.get("multichip")
    if mc is not None:  # null means "not measured", which is valid
        errors += _check(mc, MULTICHIP_SPEC, f"{where}.multichip")
        if isinstance(mc, dict):
            for j, entry in enumerate(mc.get("kernels") or []):
                errors += _check(entry, MULTICHIP_ENTRY_SPEC,
                                 f"{where}.multichip.kernels[{j}]")
    rm = payload.get("receiver_memory")
    if rm is not None:  # null means "not measured", which is valid
        errors += _check(rm, RECEIVER_MEMORY_SPEC,
                         f"{where}.receiver_memory")
        if isinstance(rm, dict):
            for j, entry in enumerate(rm.get("fleets") or []):
                errors += _check(entry, RECEIVER_FLEET_ENTRY_SPEC,
                                 f"{where}.receiver_memory.fleets[{j}]")
    vb = payload.get("variants")
    if vb is not None:  # null means "not measured", which is valid
        errors += _check(vb, VARIANT_SPEC, f"{where}.variants")
        if isinstance(vb, dict):
            for j, kc in enumerate(vb.get("kernels") or []):
                errors += _check(kc, VARIANT_KERNEL_SPEC,
                                 f"{where}.variants.kernels[{j}]")
            for j, rf in enumerate(vb.get("refusals") or []):
                errors += _check(rf, VARIANT_REFUSAL_SPEC,
                                 f"{where}.variants.refusals[{j}]")
    return errors


def _version_errors(payload) -> List[str]:
    v = payload.get("schema_version")
    if v is None:
        return ["payload.schema_version: missing"]
    if not isinstance(v, int) or isinstance(v, bool):
        return [f"payload.schema_version: expected int, "
                f"got {type(v).__name__}"]
    if v != SCHEMA_VERSION:
        return [f"payload.schema_version: expected {SCHEMA_VERSION}, "
                f"got {v}"]
    return []


def validate_bench_payload(payload) -> List[str]:
    """Validate a single-run, sweep, suite (root ``bench.py``), or
    kernel-profile payload. Top-level payloads must carry a matching
    ``schema_version``."""
    if not isinstance(payload, dict):
        return ["payload: expected a JSON object"]
    errors = _version_errors(payload)
    if payload.get("bench") == "kernel_profile_sweep":
        return errors + validate_profile_payload(payload)
    if payload.get("bench") == "engine_tick_suite":
        for key in ("steady", "churn", "contested", "partition", "delay",
                    "streaming", "fleet"):
            if key not in payload:
                errors.append(f"payload.{key}: missing")
            else:
                errors += validate_run_payload(payload[key],
                                               f"payload.{key}")
        return errors
    if "sweep" in payload:
        for i, run in enumerate(payload["sweep"]):
            errors += validate_run_payload(run, f"payload.sweep[{i}]")
        return errors
    return errors + validate_run_payload(payload)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) == 2 and argv[0] == "--progress":
        with open(argv[1], "r", encoding="utf-8") as fh:
            errors = validate_progress_stream(fh.readlines())
        if errors:
            for e in errors:
                print(f"schema violation: {e}", file=sys.stderr)
            return 1
        print(f"progress schema ok: {argv[1]}")
        return 0
    if len(argv) == 2 and argv[0] == "--streaming":
        with open(argv[1], "r", encoding="utf-8") as fh:
            errors = validate_streaming_stream(fh.readlines())
        if errors:
            for e in errors:
                print(f"schema violation: {e}", file=sys.stderr)
            return 1
        print(f"streaming schema ok: {argv[1]}")
        return 0
    if len(argv) == 2 and argv[0] in ("--load-sweep", "--status"):
        with open(argv[1], "rb") as fh:
            raw = fh.read()
        errors = [] if raw.endswith(b"\n") else \
            ["payload: file must end with a trailing newline"]
        try:
            payload = json.loads(raw)
        except ValueError as e:
            errors.append(f"payload: not JSON ({e})")
            payload = None
        if payload is not None:
            validate = (validate_load_sweep if argv[0] == "--load-sweep"
                        else validate_status_snapshot)
            errors += validate(payload)
        if errors:
            for e in errors:
                print(f"schema violation: {e}", file=sys.stderr)
            return 1
        print(f"{argv[0][2:]} schema ok: {argv[1]}")
        return 0
    if len(argv) != 1:
        print("usage: python -m rapid_tpu.telemetry.schema "
              "[--progress|--streaming|--load-sweep|--status] FILE",
              file=sys.stderr)
        return 2
    with open(argv[0], "rb") as fh:
        raw = fh.read()
    # Every JSON artifact is a line-oriented build product: tools that
    # append or concatenate them rely on the trailing newline.
    errors = [] if raw.endswith(b"\n") else \
        ["payload: file must end with a trailing newline"]
    payload = json.loads(raw)
    errors += validate_bench_payload(payload)
    if errors:
        for e in errors:
            print(f"schema violation: {e}", file=sys.stderr)
        return 1
    kind = payload.get("bench", "?")
    print(f"telemetry schema ok: {argv[0]} ({kind})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
