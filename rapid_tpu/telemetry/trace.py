"""Chrome/Perfetto trace-event export for simulation runs.

Produces the JSON trace-event format (``{"traceEvents": [...]}``) that
both ``ui.perfetto.dev`` and the legacy ``chrome://tracing`` load
directly. Two process tracks:

- **pid 1, virtual time**: the simulation rendered on the virtual-time
  axis (1 tick = ``Settings.tick_ms`` of trace time). Each tick is cut
  into five sub-slices in the engine's canonical intra-tick phase order
  — decide / deliver / flush / churn / monitor — emitted as matched B/E
  pairs only when the phase did work; instant events mark proposal
  announcements, view-change decisions, and churn activations; counter
  tracks plot membership size, alert-pipeline occupancy, and
  cut-detector fill per tick. A third thread renders the **consensus
  lineage** span tree: one outer slice per proposal (view-change
  window), phase slices — dissemination / cut_fill / fast_round —
  nested under it, and when the fast round lost, a ``fallback`` slice
  nested under the proposal it superseded with the classic 1a/1b/2a/2b
  slices inside, every one stamped with the owning proposal/epoch id.
- **pid 2, host wall-clock**: real-time spans recorded by the
  ``wall_span`` context manager (jit trace+compile, device dispatch,
  ``plan_churn``, host-side topology build). These live on a separate
  process so the microsecond axes never mix; Perfetto shows both tracks
  and the compile-vs-dispatch split is visible at a glance.

``jax_profiler_trace`` optionally wraps a region in ``jax.profiler``'s
own tracer for XLA-level detail alongside this writer's spans.
"""
from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

VIRTUAL_PID = 1
WALL_PID = 2
TID_PHASES = 1
TID_EVENTS = 2
TID_LINEAGE = 3
TID_WALL = 1

#: Intra-tick phase order, matching ``rapid_tpu.engine.step``.
PHASES = ("decide", "deliver", "flush", "churn", "monitor")


class TraceWriter:
    """Accumulates trace events; ``write`` emits Perfetto-loadable JSON."""

    def __init__(self) -> None:
        self._events: List[Dict[str, object]] = []
        self._wall_t0 = time.perf_counter()
        self._meta_done: set = set()

    # -- wall clock ------------------------------------------------------

    def wall_now_us(self) -> int:
        """Microseconds since this writer was created (wall-clock axis)."""
        return int((time.perf_counter() - self._wall_t0) * 1e6)

    # -- metadata --------------------------------------------------------

    def meta_process(self, pid: int, name: str) -> None:
        key = ("process", pid)
        if key in self._meta_done:
            return
        self._meta_done.add(key)
        self._events.append({"ph": "M", "name": "process_name", "pid": pid,
                             "tid": 0, "ts": 0, "args": {"name": name}})

    def meta_thread(self, pid: int, tid: int, name: str) -> None:
        key = ("thread", pid, tid)
        if key in self._meta_done:
            return
        self._meta_done.add(key)
        self._events.append({"ph": "M", "name": "thread_name", "pid": pid,
                             "tid": tid, "ts": 0, "args": {"name": name}})

    # -- events ----------------------------------------------------------

    def slice(self, name: str, ts_us: int, dur_us: int, pid: int, tid: int,
              args: Optional[Dict[str, object]] = None) -> None:
        """A matched B/E pair (duration slice)."""
        begin = {"ph": "B", "name": name, "pid": pid, "tid": tid,
                 "ts": int(ts_us)}
        if args:
            begin["args"] = args
        self._events.append(begin)
        self._events.append({"ph": "E", "pid": pid, "tid": tid,
                             "ts": int(ts_us) + max(1, int(dur_us))})

    def instant(self, name: str, ts_us: int, pid: int, tid: int,
                args: Optional[Dict[str, object]] = None) -> None:
        ev = {"ph": "i", "name": name, "pid": pid, "tid": tid,
              "ts": int(ts_us), "s": "t"}
        if args:
            ev["args"] = args
        self._events.append(ev)

    def counter(self, name: str, ts_us: int, pid: int,
                values: Dict[str, int]) -> None:
        self._events.append({"ph": "C", "name": name, "pid": pid, "tid": 0,
                             "ts": int(ts_us), "args": values})

    # -- output ----------------------------------------------------------

    def sorted_events(self) -> List[Dict[str, object]]:
        """Events sorted by timestamp, emission order breaking ties (so
        same-ts outer B slices stay ahead of their nested children)."""
        return sorted(self._events, key=lambda e: e["ts"])

    def to_json(self) -> Dict[str, object]:
        return {"traceEvents": self.sorted_events(),
                "displayTimeUnit": "ms"}

    def write(self, path) -> None:
        from rapid_tpu.telemetry import write_json_artifact

        write_json_artifact(path, self.to_json())


@contextmanager
def wall_span(writer: Optional[TraceWriter], name: str,
              args: Optional[Dict[str, object]] = None):
    """Time a host-side region onto the wall-clock track.

    No-op when ``writer`` is None, so instrumented call sites cost
    nothing un-traced.
    """
    if writer is None:
        yield
        return
    writer.meta_process(WALL_PID, "host wall-clock")
    writer.meta_thread(WALL_PID, TID_WALL, "host")
    t0 = writer.wall_now_us()
    try:
        yield
    finally:
        writer.slice(name, t0, writer.wall_now_us() - t0,
                     WALL_PID, TID_WALL, args)


@contextmanager
def jax_profiler_trace(log_dir: Optional[str]):
    """Wrap a region in ``jax.profiler.trace`` when a directory is given.

    The profiler writes its own TensorBoard/XPlane artifacts next to (not
    inside) this module's trace JSON; pass None to disable.
    """
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def trace_from_logs(logs, settings, writer: Optional[TraceWriter] = None,
                    pid: int = VIRTUAL_PID) -> TraceWriter:
    """Render stacked engine ``StepLog`` rows onto the virtual-time axis.

    One tick spans ``settings.tick_ms`` milliseconds of trace time, cut
    into five equal phase sub-slices; a phase is emitted only when it did
    observable work that tick, so quiescent stretches stay empty.
    """
    writer = writer or TraceWriter()
    us_per_tick = settings.tick_ms * 1000
    sub = us_per_tick // len(PHASES)

    ticks = np.asarray(logs.tick)
    ann = np.asarray(logs.announce_now)
    dec = np.asarray(logs.decide_now)
    proposal = np.asarray(logs.proposal)
    decision = np.asarray(logs.decision)
    n_member = np.asarray(logs.n_member)
    epoch = np.asarray(logs.epoch)
    flushers = np.asarray(logs.flushers)
    deliver_alive = np.asarray(logs.deliver_alive)
    probes_sent = np.asarray(logs.probes_sent)
    probes_failed = np.asarray(logs.probes_failed)
    in_flight = np.asarray(logs.alerts_in_flight)
    cut_reports = np.asarray(logs.cut_reports)
    implicit = np.asarray(logs.implicit_reports)
    tally = np.asarray(logs.vote_tally)
    quorum = np.asarray(logs.quorum)
    churned = np.asarray(logs.churn_injected)
    cfg_hi = np.asarray(logs.config_hi).astype(np.uint64)
    cfg_lo = np.asarray(logs.config_lo).astype(np.uint64)
    cfg = (cfg_hi << np.uint64(32)) | cfg_lo

    writer.meta_process(pid, "rapid-tpu virtual time")
    writer.meta_thread(pid, TID_PHASES, "tick phases")
    writer.meta_thread(pid, TID_EVENTS, "protocol events")

    for i in range(len(ticks)):
        t = int(ticks[i])
        base = t * us_per_tick
        phase_work = {
            "decide": bool(dec[i]) or int(tally[i]) > 0,
            "deliver": int(deliver_alive[i]) > 0 or bool(ann[i]),
            "flush": int(flushers[i]) > 0,
            "churn": int(churned[i]) > 0,
            "monitor": int(probes_sent[i]) > 0,
        }
        phase_args = {
            "decide": {"vote_tally": int(tally[i]),
                       "quorum": int(quorum[i]),
                       "epoch": int(epoch[i])},
            "deliver": {"cut_reports": int(cut_reports[i]),
                        "implicit_reports": int(implicit[i])},
            "flush": {"flushers": int(flushers[i])},
            "churn": {"alerts_enqueued": int(churned[i])},
            "monitor": {"probes_sent": int(probes_sent[i]),
                        "probes_failed": int(probes_failed[i])},
        }
        for j, phase in enumerate(PHASES):
            if phase_work[phase]:
                writer.slice(phase, base + j * sub, sub, pid, TID_PHASES,
                             phase_args[phase])
        if ann[i]:
            writer.instant("proposal", base + sub + sub // 2, pid,
                           TID_EVENTS,
                           {"tick": t, "slots": int(proposal[i].sum())})
        if dec[i]:
            writer.instant("view_change", base + sub // 2, pid, TID_EVENTS,
                           {"tick": t, "slots": int(decision[i].sum()),
                            "n_member": int(n_member[i]),
                            "config_id": f"{int(cfg[i]):#x}"})
        if churned[i]:
            writer.instant("churn_activation", base + 3 * sub + sub // 2,
                           pid, TID_EVENTS,
                           {"tick": t, "slots": int(churned[i])})
        writer.counter("membership", base, pid, {"n": int(n_member[i])})
        writer.counter("alerts_in_flight", base, pid,
                       {"batches": int(in_flight[i])})
        writer.counter("cut_reports", base, pid,
                       {"cells": int(cut_reports[i])})
    lineage_trace_from_logs(logs, settings, writer, pid=pid)
    return writer


def lineage_trace_from_logs(logs, settings,
                            writer: Optional[TraceWriter] = None,
                            pid: int = VIRTUAL_PID) -> TraceWriter:
    """Render the lineage span tree of ``StepLog`` rows as nested slices.

    One outer slice per proposal (view-change window), with the phase
    slices laid end-to-end inside it at their folded durations, and the
    classic 1a/1b/2a/2b slices nested inside the ``fallback`` slice of
    the fast round they superseded. Every nested slice carries the
    owning ``proposal``/``epoch`` id in its args, so Perfetto groups the
    chain under its proposal instead of rendering flat phase slices.

    Nested slices are shaved 1–2 us short of their parent's end: the
    trace-event format closes same-``ts`` E events in emission order,
    and the parent's E is emitted first.
    """
    from rapid_tpu.telemetry import lineage as lineage_lib

    writer = writer or TraceWriter()
    us_per_tick = settings.tick_ms * 1000
    ticks = np.asarray(logs.tick)
    epoch = np.asarray(logs.epoch)
    spans = lineage_lib.fold_spans(lineage_lib.engine_phase_columns(logs))
    if spans:
        writer.meta_process(pid, "rapid-tpu virtual time")
        writer.meta_thread(pid, TID_LINEAGE, "consensus lineage")
    for k, span in enumerate(spans):
        if span["truncated"]:
            continue
        s, d = int(span["window_start"]), int(span["decide_tick"])
        di = np.flatnonzero(ticks == d)
        e = int(epoch[int(di[0])]) if di.size else -1
        own = {"proposal": k, "epoch": e}
        dur = span["durations"]
        writer.slice(f"proposal {k}", (s + 1) * us_per_tick,
                     (d - s) * us_per_tick, pid, TID_LINEAGE,
                     {**own, "fallback": span["fallback"],
                      "durations": dict(dur)})
        cur = (s + 1) * us_per_tick
        fb_ticks = dur["fallback_wait"] + dur["classic_phase_ticks"]
        for name, n_ticks in (("dissemination", dur["dissemination_ticks"]),
                              ("cut_fill", dur["cut_fill_ticks"]),
                              ("fast_round", dur["fast_vote_wait"]),
                              ("fallback", fb_ticks)):
            if n_ticks > 0:
                writer.slice(name, cur, n_ticks * us_per_tick - 1, pid,
                             TID_LINEAGE, own)
            cur += n_ticks * us_per_tick
        if fb_ticks > 0:
            # Classic phases nest inside the fallback slice; its region
            # opens one tick after the resolved fast-round boundary.
            fb_open = d - fb_ticks + 1
            for pname in ("phase1a", "phase1b", "phase2a", "phase2b"):
                m = span["milestones"].get(pname + "_tick")
                if m is not None and fb_open <= m <= d:
                    writer.slice(pname, m * us_per_tick,
                                 us_per_tick - 2, pid, TID_LINEAGE, own)
    return writer
