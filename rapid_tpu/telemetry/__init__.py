"""Observability for rapid-tpu: metrics, traces, and divergence forensics.

Three layers over the same per-tick observables (Rapid §6's evaluation
quantities — alert batches in flight, cut-detector fill between L and H,
fast-round quorum progress, time-to-view-change):

- ``metrics`` — ``TickMetrics`` normalizes engine ``StepLog`` rows and
  oracle ``NetworkCounters`` deltas into one record stream (JSONL
  round-trippable); ``summarize`` folds a stream into the per-run
  ``RunSummary`` the benchmarks embed in their JSON payloads.
- ``trace`` — Chrome/Perfetto trace-event export: virtual-time phase
  slices and protocol instants from a run's logs, plus wall-clock spans
  (``wall_span``) around jit trace, device dispatch, and churn planning.
- ``forensics`` — first-divergence reports (tick, field, both values,
  trailing context) raised as ``DivergenceError`` by the differential
  harness instead of a bare AssertionError, with a JSONL artifact.
- ``schema`` — structural validation of BENCH payloads for the tier-1
  smoke step.
"""
from rapid_tpu.telemetry.forensics import (
    Divergence,
    DivergenceError,
    DivergenceReport,
)
from rapid_tpu.telemetry.metrics import (
    COUNTER_FIELDS,
    UNOBSERVED,
    RunSummary,
    TickMetrics,
    counters_equal,
    engine_metrics,
    fleet_summaries,
    merge_summaries,
    oracle_metrics,
    read_jsonl,
    summarize,
    summary_distributions,
    write_jsonl,
)
from rapid_tpu.telemetry.trace import (
    TraceWriter,
    jax_profiler_trace,
    trace_from_logs,
    wall_span,
)

__all__ = [
    "COUNTER_FIELDS",
    "Divergence",
    "DivergenceError",
    "DivergenceReport",
    "RunSummary",
    "TickMetrics",
    "TraceWriter",
    "UNOBSERVED",
    "counters_equal",
    "engine_metrics",
    "fleet_summaries",
    "jax_profiler_trace",
    "merge_summaries",
    "oracle_metrics",
    "read_jsonl",
    "summarize",
    "summary_distributions",
    "trace_from_logs",
    "wall_span",
    "write_jsonl",
]
