"""Observability for rapid-tpu: metrics, traces, and divergence forensics.

Three layers over the same per-tick observables (Rapid §6's evaluation
quantities — alert batches in flight, cut-detector fill between L and H,
fast-round quorum progress, time-to-view-change):

- ``metrics`` — ``TickMetrics`` normalizes engine ``StepLog`` rows and
  oracle ``NetworkCounters`` deltas into one record stream (JSONL
  round-trippable); ``summarize`` folds a stream into the per-run
  ``RunSummary`` the benchmarks embed in their JSON payloads.
- ``trace`` — Chrome/Perfetto trace-event export: virtual-time phase
  slices and protocol instants from a run's logs, plus wall-clock spans
  (``wall_span``) around jit trace, device dispatch, and churn planning.
- ``forensics`` — first-divergence reports (tick, field, both values,
  trailing context) raised as ``DivergenceError`` by the differential
  harness instead of a bare AssertionError, with a JSONL artifact.
- ``schema`` — structural validation of BENCH payloads for the tier-1
  smoke step.

Every artifact the repo writes — bench payloads, campaign payloads,
Perfetto traces, TickMetrics streams, divergence forensics — goes
through the two writers below, so the line-oriented contract (each file
ends with exactly one trailing newline; ``schema.main`` rejects
artifacts without it) is enforced in one place instead of by
convention at every call site.
"""
import json as _json


def json_artifact_line(payload, *, sort_keys: bool = False, indent=None,
                       separators=None, default=None) -> str:
    """One JSON document as a newline-terminated string."""
    return _json.dumps(payload, sort_keys=sort_keys, indent=indent,
                       separators=separators, default=default) + "\n"


def write_json_artifact(path, payload, *, sort_keys: bool = False,
                        indent=None, default=None) -> None:
    """Write one JSON artifact, newline-terminated.

    The single chokepoint for whole-document artifacts (bench payloads,
    campaign payloads, trace JSON, baselines): tools that append to,
    concatenate, or line-count these files rely on the trailing newline.
    """
    with open(path, "w") as fh:
        fh.write(json_artifact_line(payload, sort_keys=sort_keys,
                                    indent=indent, default=default))


def write_jsonl_artifact(path, records, *, sort_keys: bool = True,
                         default=None) -> None:
    """Write an iterable of records as JSONL, one newline-terminated
    line per record (TickMetrics streams, divergence forensics)."""
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json_artifact_line(rec, sort_keys=sort_keys,
                                        default=default))


# The writers above are defined before the submodule imports below so
# submodules can ``from rapid_tpu.telemetry import write_json_artifact``
# during package init without a circular-import trap.
from rapid_tpu.telemetry.forensics import (  # noqa: E402
    Divergence,
    DivergenceError,
    DivergenceReport,
)
from rapid_tpu.telemetry.metrics import (  # noqa: E402
    COUNTER_FIELDS,
    UNOBSERVED,
    RunSummary,
    TickMetrics,
    counters_equal,
    engine_metrics,
    fleet_summaries,
    merge_summaries,
    oracle_metrics,
    read_jsonl,
    summarize,
    summary_distributions,
    write_jsonl,
)
from rapid_tpu.telemetry.trace import (  # noqa: E402
    TraceWriter,
    jax_profiler_trace,
    trace_from_logs,
    wall_span,
)

__all__ = [
    "COUNTER_FIELDS",
    "Divergence",
    "DivergenceError",
    "DivergenceReport",
    "RunSummary",
    "TickMetrics",
    "TraceWriter",
    "UNOBSERVED",
    "counters_equal",
    "engine_metrics",
    "fleet_summaries",
    "jax_profiler_trace",
    "json_artifact_line",
    "merge_summaries",
    "oracle_metrics",
    "read_jsonl",
    "summarize",
    "summary_distributions",
    "trace_from_logs",
    "wall_span",
    "write_json_artifact",
    "write_jsonl",
    "write_jsonl_artifact",
]
