"""Consensus lineage: phase-attributed view-change spans.

Rapid's membership pipeline runs alert dissemination -> cut-detector
fill -> fast-quorum vote -> (optionally) classic-Paxos fallback, but the
telemetry stack historically reported only the end-to-end
``ticks_to_view_change`` tail.  This module folds the per-tick phase
streams the system already records into per-view-change **lineage
spans**: the boundary tick of every pipeline phase, the derived phase
durations, and (in per-receiver mode) the critical straggler edge plus
the ``DelayRule`` responsible for it.

Every source of per-tick phase activity gets a builder producing the
same :class:`PhaseColumns` shape, so one fold serves them all:

- :func:`engine_phase_columns` — jitted-scan ``StepLog`` factor logs
  (products of sender x recipient factors, exactly as ``diff.py``
  expands them for the counter differential);
- :func:`receiver_phase_columns` — per-receiver ``ReceiverStepLog``
  exact counters;
- :func:`counter_phase_columns` — host-oracle / adversary-referee
  counter dict streams (``tick_history`` + ``consensus_history``) and a
  view-event stream;
- :func:`gauge_phase_columns` — ``TickMetrics`` gauge rows (streaming
  service path);
- :func:`ring_phase_columns` — flight-recorder ``[W, G]`` gauge rings
  (no per-phase ``px_*`` columns -> classic-phase boundaries are marked
  unobservable, never guessed).

The fold itself (:func:`fold_spans`) is pure host-side numpy over those
columns; lineage is *derived* data over streams already proven
bit-identical by the engine differentials, so its exactness is
inherited, not asserted.  ``diff.run_lineage_differential`` closes the
loop by re-deriving spans independently on oracle and engine sides.

Duration identity (enforced for every non-truncated span)::

    dissemination_ticks + cut_fill_ticks + fast_vote_wait
        + fallback_wait + classic_phase_ticks == ticks_to_view_change

Milestones that did not occur resolve to the next observed boundary, so
the telescoping sum always closes without inventing ticks.
"""
from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: Span duration fields, in pipeline order.
LINEAGE_DURATIONS = (
    "dissemination_ticks",
    "cut_fill_ticks",
    "fast_vote_wait",
    "fallback_wait",
    "classic_phase_ticks",
)

#: Phase boundary milestones recorded per span (``None`` = not observed).
LINEAGE_MILESTONES = (
    "first_alert_tick",
    "first_report_tick",
    "announce_tick",
    "first_vote_tick",
    "fallback_armed_tick",
    "phase1a_tick",
    "phase1b_tick",
    "phase2a_tick",
    "phase2b_tick",
)

#: Milestones that only the engine can observe (timer gauges); dropped by
#: :func:`comparable` so oracle/engine span streams diff clean.
_ENGINE_ONLY_MILESTONES = ("fallback_armed_tick",)


# ---------------------------------------------------------------------------
# Phase columns
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PhaseColumns:
    """Per-tick phase activity columns (numpy, ``[T]`` or ``[F, T]``).

    ``phase*_sent`` columns are ``None`` when the source stream cannot
    observe classic-phase traffic (flight-recorder rings); the fold then
    refuses to place classic-phase boundaries instead of guessing.
    ``timers_armed`` is engine-only (``None`` on oracle streams).
    """

    tick: np.ndarray
    alert_sent: np.ndarray
    alert_delivered: np.ndarray
    fast_vote_sent: np.ndarray
    phase1a_sent: Optional[np.ndarray]
    phase1b_sent: Optional[np.ndarray]
    phase2a_sent: Optional[np.ndarray]
    phase2b_sent: Optional[np.ndarray]
    announce: np.ndarray
    decide: np.ndarray
    timers_armed: Optional[np.ndarray] = None

    @property
    def phases_observed(self) -> bool:
        return self.phase1a_sent is not None

    def member(self, j: int) -> "PhaseColumns":
        """Row ``j`` of ``[F, T]``-shaped columns as a ``[T]`` view."""
        vals = {}
        for f in fields(self):
            v = getattr(self, f.name)
            vals[f.name] = None if v is None else np.asarray(v)[j]
        return PhaseColumns(**vals)


def _i64(x) -> np.ndarray:
    return np.asarray(x).astype(np.int64)


def engine_phase_columns(logs) -> PhaseColumns:
    """Columns from jitted-scan ``StepLog`` factor logs (``[T]`` or
    ``[F, T]``), expanding the same sender x recipient products as the
    counter differential in ``engine.diff``."""
    fast_vote = (_i64(logs.vote_senders) * _i64(logs.vote_recipients)
                 + _i64(logs.pxvote_senders) * _i64(logs.pxvote_recipients))
    return PhaseColumns(
        tick=_i64(logs.tick),
        alert_sent=_i64(logs.flushers) * _i64(logs.flush_recipients),
        alert_delivered=_i64(logs.flushers_alive) * _i64(logs.deliver_alive),
        fast_vote_sent=fast_vote,
        phase1a_sent=_i64(logs.px1a_senders) * _i64(logs.px1a_recipients),
        phase1b_sent=_i64(logs.px1b_senders),
        phase2a_sent=_i64(logs.px2a_senders) * _i64(logs.px2a_recipients),
        phase2b_sent=_i64(logs.px2b_senders) * _i64(logs.px2b_recipients),
        announce=np.asarray(logs.announce_now).astype(bool),
        decide=np.asarray(logs.decide_now).astype(bool),
        timers_armed=_i64(logs.px_timers_armed),
    )


def receiver_phase_columns(mlog) -> PhaseColumns:
    """Columns from one member's ``ReceiverStepLog`` exact counters.

    The receiver kernel counts per-phase traffic directly; alert traffic
    is the remainder of the total over the consensus classes.
    """
    fv = _i64(mlog.fv_sent)
    p1a, p1b = _i64(mlog.p1a_sent), _i64(mlog.p1b_sent)
    p2a, p2b = _i64(mlog.p2a_sent), _i64(mlog.p2b_sent)
    phase_sent = fv + p1a + p1b + p2a + p2b
    phase_delivered = (_i64(mlog.fv_delivered) + _i64(mlog.p1a_delivered)
                       + _i64(mlog.p1b_delivered) + _i64(mlog.p2a_delivered)
                       + _i64(mlog.p2b_delivered))
    return PhaseColumns(
        tick=_i64(mlog.tick),
        alert_sent=_i64(mlog.sent) - phase_sent,
        alert_delivered=_i64(mlog.delivered) - phase_delivered,
        fast_vote_sent=fv,
        phase1a_sent=p1a,
        phase1b_sent=p1b,
        phase2a_sent=p2a,
        phase2b_sent=p2b,
        announce=np.asarray(mlog.announce).astype(bool).any(axis=-1),
        decide=np.asarray(mlog.decide).astype(bool).any(axis=-1),
        timers_armed=None,
    )


_PHASE_KEYS = ("fast_vote", "phase1a", "phase1b", "phase2a", "phase2b")


def _event_tick_kind(ev) -> Tuple[int, str]:
    if hasattr(ev, "tick"):
        return int(ev.tick), str(ev.kind)
    return int(ev[0]), str(ev[1])


def counter_phase_columns(tick_history: Sequence[Dict[str, int]],
                          phase_history: Sequence[Dict[str, int]],
                          events, start_tick: int = 0) -> PhaseColumns:
    """Columns from host-oracle (or adversary-referee) counter streams.

    ``tick_history[i]`` / ``phase_history[i]`` describe tick
    ``start_tick + 1 + i``; ``events`` is a view-event stream (objects
    with ``.tick``/``.kind`` or ``(tick, kind, ...)`` tuples) supplying
    the announce/decide flags.
    """
    t = len(tick_history)
    ticks = start_tick + 1 + np.arange(t, dtype=np.int64)
    sent = np.array([d.get("sent", 0) for d in tick_history], np.int64)
    delivered = np.array([d.get("delivered", 0) for d in tick_history],
                         np.int64)
    phase = {}
    for key in _PHASE_KEYS:
        phase[key + "_sent"] = np.array(
            [phase_history[i].get(key + "_sent", 0) if i < len(phase_history)
             else 0 for i in range(t)], np.int64)
        phase[key + "_delivered"] = np.array(
            [phase_history[i].get(key + "_delivered", 0)
             if i < len(phase_history) else 0 for i in range(t)], np.int64)
    phase_sent = sum(phase[k + "_sent"] for k in _PHASE_KEYS)
    phase_delivered = sum(phase[k + "_delivered"] for k in _PHASE_KEYS)
    announce = np.zeros(t, bool)
    decide = np.zeros(t, bool)
    for ev in events:
        tick, kind = _event_tick_kind(ev)
        i = tick - start_tick - 1
        if 0 <= i < t:
            if kind == "proposal":
                announce[i] = True
            elif kind == "view_change":
                decide[i] = True
    return PhaseColumns(
        tick=ticks,
        alert_sent=sent - phase_sent,
        alert_delivered=delivered - phase_delivered,
        fast_vote_sent=phase["fast_vote_sent"],
        phase1a_sent=phase["phase1a_sent"],
        phase1b_sent=phase["phase1b_sent"],
        phase2a_sent=phase["phase2a_sent"],
        phase2b_sent=phase["phase2b_sent"],
        announce=announce,
        decide=decide,
        timers_armed=None,
    )


def _gauge(v: int) -> int:
    # UNOBSERVED gauges are -1; clamp so activity tests stay boolean-clean.
    return max(int(v), 0)


def gauge_phase_columns(rows) -> PhaseColumns:
    """Columns from ``TickMetrics`` gauge rows (streaming service path).

    Gauges are occupancy/level signals rather than exact message counts,
    but first-positive ticks coincide with the phase boundaries, which
    is all the fold consumes.
    """
    return PhaseColumns(
        tick=np.array([r.tick for r in rows], np.int64),
        alert_sent=np.array([_gauge(r.alerts_in_flight) for r in rows],
                            np.int64),
        alert_delivered=np.array(
            [_gauge(r.cut_reports) + _gauge(r.implicit_reports)
             for r in rows], np.int64),
        fast_vote_sent=np.array(
            [_gauge(r.vote_tally) + _gauge(r.px_fast_vote_sent)
             for r in rows], np.int64),
        phase1a_sent=np.array([_gauge(r.px_phase1a_sent) for r in rows],
                              np.int64),
        phase1b_sent=np.array([_gauge(r.px_phase1b_sent) for r in rows],
                              np.int64),
        phase2a_sent=np.array([_gauge(r.px_phase2a_sent) for r in rows],
                              np.int64),
        phase2b_sent=np.array([_gauge(r.px_phase2b_sent) for r in rows],
                              np.int64),
        announce=np.array([bool(r.announce) for r in rows], bool),
        decide=np.array([bool(r.decide) for r in rows], bool),
        timers_armed=np.array([_gauge(r.px_timers_armed) for r in rows],
                              np.int64),
    )


def ring_phase_columns(payload: Dict[str, object]) -> PhaseColumns:
    """Columns from a flight-recorder payload's ``[W, G]`` gauge ring.

    The ring records no per-phase ``px_*`` columns, so classic-phase
    boundaries are unobservable (``phase*_sent`` are ``None``); the fold
    degrades those spans honestly instead of inventing boundaries.
    """
    names = list(payload["gauges"])
    rows = np.asarray(payload["rows"], np.int64)
    col = {name: rows[:, i] for i, name in enumerate(names)}
    clip = lambda a: np.maximum(a, 0)
    return PhaseColumns(
        tick=col["tick"],
        alert_sent=clip(col["alerts_in_flight"]),
        alert_delivered=clip(col["cut_reports"]),
        fast_vote_sent=clip(col["vote_tally"]),
        phase1a_sent=None,
        phase1b_sent=None,
        phase2a_sent=None,
        phase2b_sent=None,
        announce=col["announces"] > 0,
        decide=col["decides"] > 0,
        timers_armed=clip(col["px_timers_armed"]),
    )


# ---------------------------------------------------------------------------
# Span fold
# ---------------------------------------------------------------------------


def _blank_milestones() -> Dict[str, Optional[int]]:
    return {name: None for name in LINEAGE_MILESTONES}


def _blank_durations() -> Dict[str, Optional[int]]:
    return {name: None for name in LINEAGE_DURATIONS}


def _resolve_durations(window_start: int, ms: Dict[str, Optional[int]],
                       decide_tick: int, phases_observed: bool,
                       fallback: bool) -> Dict[str, int]:
    """Telescoping phase durations; always sums to ``decide - start``.

    Missing boundaries resolve to the next observed one, and each is
    clamped monotone into ``[window_start, decide_tick]`` so a late
    first-seen (e.g. a re-flush) can never drive a duration negative.
    """
    s, d = window_start, decide_tick
    a = ms["announce_tick"]
    if a is None:
        a = ms["first_vote_tick"]
    f = ms["phase1a_tick"] if phases_observed else None
    if f is None:
        f = d
    if a is None:
        a = f
    r = ms["first_report_tick"]
    if r is None:
        r = a
    r = min(max(r, s), d)
    a = min(max(a, r), d)
    f = min(max(f, a), d)
    out = {
        "dissemination_ticks": r - s,
        "cut_fill_ticks": a - r,
        "fast_vote_wait": 0 if fallback else d - a,
        "fallback_wait": f - a if fallback else 0,
        "classic_phase_ticks": d - f,
    }
    if fallback and not phases_observed:
        # Ring streams cannot see the 1a boundary: the classic share is
        # folded into fallback_wait (f == d above), keeping the sum exact.
        out["classic_phase_ticks"] = 0
    return out


def _make_span(window_start: Optional[int], ms: Dict[str, Optional[int]],
               decide_tick: int, phases_observed: bool,
               truncated: bool = False) -> Dict[str, object]:
    if truncated:
        return {
            "window_start": None,
            "decide_tick": int(decide_tick),
            "ticks_to_view_change": None,
            "fallback": False,
            "truncated": True,
            "milestones": _blank_milestones(),
            "durations": _blank_durations(),
            "critical_path": None,
        }
    assert window_start is not None
    if phases_observed:
        fallback = ms["phase1a_tick"] is not None
    else:
        fallback = ms["fallback_armed_tick"] is not None
    return {
        "window_start": int(window_start),
        "decide_tick": int(decide_tick),
        "ticks_to_view_change": int(decide_tick - window_start),
        "fallback": bool(fallback),
        "truncated": False,
        "milestones": dict(ms),
        "durations": _resolve_durations(window_start, ms, decide_tick,
                                        phases_observed, fallback),
        "critical_path": None,
    }


def _first_positive(arr: Optional[np.ndarray], sl: slice,
                    ticks: np.ndarray) -> Optional[int]:
    if arr is None:
        return None
    seg = np.asarray(arr[sl])
    nz = np.flatnonzero(seg > 0)
    if nz.size == 0:
        return None
    return int(ticks[sl][nz[0]])


def fold_spans(cols: PhaseColumns, *, start_tick: Optional[int] = None,
               truncated_head: bool = False) -> List[Dict[str, object]]:
    """Fold per-tick phase columns into per-view-change span records.

    Windows run ``(previous decide, decide]``; the first window opens at
    ``start_tick`` (default: one tick before the first recorded row).
    With ``truncated_head=True`` the first window's opening is unknown
    (ring evicted it): that span is emitted with ``truncated: true`` and
    no milestone/duration claims — explicit ignorance over wrong ticks.
    """
    ticks = np.asarray(cols.tick)
    if ticks.ndim != 1:
        raise ValueError("fold_spans needs [T] columns; use "
                         "PhaseColumns.member(j) for fleet logs")
    if ticks.size == 0:
        return []
    if start_tick is None:
        start_tick = int(ticks[0]) - 1
    milestone_cols = (
        ("first_alert_tick", cols.alert_sent),
        ("first_report_tick", cols.alert_delivered),
        ("first_vote_tick", cols.fast_vote_sent),
        ("fallback_armed_tick", cols.timers_armed),
        ("phase1a_tick", cols.phase1a_sent),
        ("phase1b_tick", cols.phase1b_sent),
        ("phase2a_tick", cols.phase2a_sent),
        ("phase2b_tick", cols.phase2b_sent),
    )
    spans: List[Dict[str, object]] = []
    begin = 0
    window_start = int(start_tick)
    for di in np.flatnonzero(np.asarray(cols.decide)):
        sl = slice(begin, int(di) + 1)
        ms = _blank_milestones()
        for name, arr in milestone_cols:
            ms[name] = _first_positive(arr, sl, ticks)
        ann = np.flatnonzero(np.asarray(cols.announce)[sl])
        if ann.size:
            ms["announce_tick"] = int(ticks[sl][ann[0]])
        decide_tick = int(ticks[di])
        truncate_this = truncated_head and not spans
        spans.append(_make_span(window_start, ms, decide_tick,
                                cols.phases_observed,
                                truncated=truncate_this))
        window_start = decide_tick
        begin = int(di) + 1
    return spans


def lineage_from_recorder(payload: Dict[str, object]
                          ) -> List[Dict[str, object]]:
    """Spans from a flight-recorder payload, with honest truncation.

    When the ring evicted early ticks (``ticks_recorded > window``) the
    first in-ring decide's window opened before the retained range, so
    that span is marked ``truncated``.
    """
    rows = payload.get("rows") or []
    if not rows:
        return []
    cols = ring_phase_columns(payload)
    evicted = int(payload.get("ticks_recorded", len(rows))) > len(rows)
    return fold_spans(cols, truncated_head=evicted)


# ---------------------------------------------------------------------------
# Comparison + summaries
# ---------------------------------------------------------------------------


def comparable(span: Dict[str, object]) -> Dict[str, object]:
    """Projection of a span to oracle-observable fields, for diffing."""
    ms = {k: v for k, v in span["milestones"].items()
          if k not in _ENGINE_ONLY_MILESTONES}
    return {
        "window_start": span["window_start"],
        "decide_tick": span["decide_tick"],
        "ticks_to_view_change": span["ticks_to_view_change"],
        "fallback": span["fallback"],
        "truncated": span["truncated"],
        "milestones": ms,
        "durations": dict(span["durations"]),
    }


def lineage_summary(spans: Sequence[Dict[str, object]]) -> Dict[str, object]:
    """Distribution summary of a span population (schema
    ``LINEAGE_SUMMARY_SPEC``)."""
    from rapid_tpu.telemetry.metrics import _dist

    durations = {}
    for name in LINEAGE_DURATIONS:
        vals = [s["durations"][name] for s in spans
                if s["durations"][name] is not None]
        durations[name] = _dist(vals)
    return {
        "spans": len(spans),
        "fallbacks": sum(1 for s in spans if s["fallback"]),
        "truncated": sum(1 for s in spans if s["truncated"]),
        "durations": durations,
    }


# ---------------------------------------------------------------------------
# Critical-path attribution (per-receiver mode)
# ---------------------------------------------------------------------------


def _rule_for_edge(delays, seed: int, src: int, dst: int,
                   tick: int) -> Optional[int]:
    from rapid_tpu.faults import delay_of_slots

    for i, rule in enumerate(delays):
        if not rule.active(tick):
            continue
        fwd = src in rule.src_slots and dst in rule.dst_slots
        rev = (rule.reverse_delay_ticks >= 0 and src in rule.dst_slots
               and dst in rule.src_slots)
        if (fwd or rev) and delay_of_slots([rule], seed, src, dst, tick) > 0:
            return i
    return None


def receiver_critical_path(mlog, span: Dict[str, object],
                           schedule) -> Optional[Dict[str, object]]:
    """Last-arriving report/vote edge into the deciding slot of ``span``.

    Recomputes per-edge delivery delay with the exact host rule
    (``faults.delay_of_slots``) over the per-slot announce masks of one
    member's ``ReceiverStepLog``: for every slot whose view-change start
    (announce) falls in the span's window, the edge to the deciding slot
    arrives at ``start + 1 + delay``; the critical edge is the latest
    arrival at or before the decide tick (ties -> lowest source slot).
    Returns ``None`` when the span is truncated or no edge is visible.
    """
    from rapid_tpu.faults import delay_of_slots

    if span["truncated"] or span["window_start"] is None:
        return None
    s, d = int(span["window_start"]), int(span["decide_tick"])
    ticks = np.asarray(mlog.tick)
    announce = np.asarray(mlog.announce).astype(bool)
    decide = np.asarray(mlog.decide).astype(bool)
    di = np.flatnonzero(ticks == d)
    if di.size == 0:
        return None
    deciders = np.flatnonzero(decide[int(di[0])])
    if deciders.size == 0:
        return None
    dst = int(deciders[0])
    window = (ticks > s) & (ticks <= d)
    if not window.any():
        return None
    win_ticks = ticks[window]
    win_ann = announce[window]
    best = None  # (arrival, -src) maximised
    for src in np.flatnonzero(win_ann.any(axis=0)):
        src = int(src)
        first = int(win_ticks[np.flatnonzero(win_ann[:, src])[0]])
        arrival = first + 1 + delay_of_slots(schedule.delays, schedule.seed,
                                             src, dst, first)
        if arrival > d:
            continue
        key = (arrival, -src)
        if best is None or key > best[0]:
            best = (key, src, first, arrival)
    if best is None:
        return None
    _, src, send_tick, arrival = best
    return {
        "src": src,
        "dst": dst,
        "send_tick": send_tick,
        "arrival_tick": arrival,
        "delay_rule": _rule_for_edge(schedule.delays, schedule.seed, src,
                                     dst, send_tick),
    }


# ---------------------------------------------------------------------------
# Streaming fold (chunk-boundary safe)
# ---------------------------------------------------------------------------


class LineageFold:
    """Stateful lineage fold over streaming chunk columns.

    Carries the open window (start tick + partial first-seen milestones)
    across chunk boundaries, so folding a trajectory in chunks of any
    size yields the identical span stream.  State round-trips through
    ``state_dict``/``from_state`` for checkpoint host blobs.
    """

    def __init__(self, start_tick: int = 0) -> None:
        self._window_start = int(start_tick)
        self._ms = _blank_milestones()
        self._phases_observed = True

    def fold(self, rows) -> List[Dict[str, object]]:
        """Fold ``TickMetrics`` rows; returns spans closed this chunk."""
        if not rows:
            return []
        return self.fold_columns(gauge_phase_columns(rows))

    def fold_columns(self, cols: PhaseColumns) -> List[Dict[str, object]]:
        ticks = np.asarray(cols.tick)
        if ticks.size == 0:
            return []
        self._phases_observed = cols.phases_observed
        milestone_cols = (
            ("first_alert_tick", cols.alert_sent),
            ("first_report_tick", cols.alert_delivered),
            ("first_vote_tick", cols.fast_vote_sent),
            ("fallback_armed_tick", cols.timers_armed),
            ("phase1a_tick", cols.phase1a_sent),
            ("phase1b_tick", cols.phase1b_sent),
            ("phase2a_tick", cols.phase2a_sent),
            ("phase2b_tick", cols.phase2b_sent),
        )
        spans: List[Dict[str, object]] = []
        begin = 0
        decide_idx = np.flatnonzero(np.asarray(cols.decide))
        for di in list(decide_idx) + [None]:
            end = ticks.size if di is None else int(di) + 1
            sl = slice(begin, end)
            for name, arr in milestone_cols:
                if self._ms[name] is None:
                    self._ms[name] = _first_positive(arr, sl, ticks)
            if self._ms["announce_tick"] is None:
                ann = np.flatnonzero(np.asarray(cols.announce)[sl])
                if ann.size:
                    self._ms["announce_tick"] = int(ticks[sl][ann[0]])
            if di is None:
                break
            decide_tick = int(ticks[int(di)])
            spans.append(_make_span(self._window_start, self._ms,
                                    decide_tick, self._phases_observed))
            self._window_start = decide_tick
            self._ms = _blank_milestones()
            begin = end
        return spans

    # -- checkpoint state ------------------------------------------------

    def state_dict(self) -> Dict[str, object]:
        return {
            "window_start": self._window_start,
            "milestones": dict(self._ms),
            "phases_observed": self._phases_observed,
        }

    @classmethod
    def from_state(cls, state: Dict[str, object]) -> "LineageFold":
        fold = cls(int(state["window_start"]))
        ms = _blank_milestones()
        for k, v in dict(state.get("milestones", {})).items():
            if k in ms:
                ms[k] = None if v is None else int(v)
        fold._ms = ms
        fold._phases_observed = bool(state.get("phases_observed", True))
        return fold
