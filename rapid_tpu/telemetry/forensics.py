"""Divergence forensics: first-divergence reports for the differentials.

``rapid_tpu.engine.diff`` used to fail with a bare ``AssertionError``
dumping both event streams; at N=256 that is a wall of tuples with the
actual divergence buried somewhere inside. This module locates the
*first* point where engine and oracle disagree — by tick, then by field —
and packages it with the last few ``TickMetrics``/``ViewEvent`` records
of context as:

- a readable exception message (``DivergenceError``, still an
  ``AssertionError`` so existing harnesses keep working), and
- an optional JSONL artifact (context records first, the divergence
  record last) for offline diffing with standard tools.

The finders return ``Divergence`` records; ``earliest`` picks the one
with the smallest tick (list order breaking ties, so callers put their
highest-signal comparison first).
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

#: Event fields compared in order; the first mismatch names the field.
_EVENT_FIELDS = ("tick", "kind", "slots", "config_id")


def _jsonable(v):
    if isinstance(v, (tuple, frozenset, set)):
        return sorted(v) if isinstance(v, (set, frozenset)) else list(v)
    return v


@dataclass
class Divergence:
    """The first disagreeing (tick, field) pair between two streams."""

    tick: int
    field: str
    engine: object  # our side's value (engine, or planner for plan_* fields)
    oracle: object  # the reference side's value


@dataclass
class DivergenceReport:
    """A located divergence plus trailing context records."""

    tick: int
    field: str
    engine: object
    oracle: object
    context: List[Dict[str, object]] = field(default_factory=list)

    def as_dict(self) -> Dict[str, object]:
        return {"record": "divergence", "tick": self.tick,
                "field": self.field,
                "engine": _jsonable(self.engine),
                "oracle": _jsonable(self.oracle)}

    def render(self) -> str:
        lines = [
            f"engine diverged from oracle at tick {self.tick}, "
            f"field {self.field!r}:",
            f"  engine: {self.engine!r}",
            f"  oracle: {self.oracle!r}",
        ]
        if self.context:
            lines.append(f"last {len(self.context)} records before the "
                         f"divergence:")
            for rec in self.context:
                lines.append("  " + json.dumps(rec, sort_keys=True,
                                               default=str))
        return "\n".join(lines)

    def write_jsonl(self, path) -> None:
        """Context records first, the divergence record last."""
        from rapid_tpu.telemetry import write_jsonl_artifact

        write_jsonl_artifact(path, [*self.context, self.as_dict()],
                             default=str)


class DivergenceError(AssertionError):
    """Raised by ``assert_identical`` with the located first divergence."""

    def __init__(self, report: DivergenceReport,
                 artifact: Optional[str] = None) -> None:
        self.report = report
        self.artifact = artifact
        msg = report.render()
        if artifact:
            msg += f"\nforensics artifact: {artifact}"
        super().__init__(msg)


# ---------------------------------------------------------------------------
# finders
# ---------------------------------------------------------------------------


def events_divergence(engine_events: Sequence, oracle_events: Sequence,
                      prefix: str = "events") -> Optional[Divergence]:
    """First field-level mismatch between two ViewEvent streams."""
    for i, (ev, ov) in enumerate(zip(engine_events, oracle_events)):
        for f in _EVENT_FIELDS:
            evf, ovf = getattr(ev, f), getattr(ov, f)
            if evf != ovf:
                return Divergence(min(ev.tick, ov.tick),
                                  f"{prefix}[{i}].{f}", evf, ovf)
    if len(engine_events) != len(oracle_events):
        i = min(len(engine_events), len(oracle_events))
        longer = engine_events if len(engine_events) > len(oracle_events) \
            else oracle_events
        return Divergence(longer[i].tick, f"{prefix}.length",
                          len(engine_events), len(oracle_events))
    return None


def counters_divergence(engine_counters: Sequence[Dict[str, int]],
                        oracle_counters: Sequence[Dict[str, int]],
                        start_tick: int = 0) -> Optional[Divergence]:
    """First per-tick message-counter mismatch (tick = start_tick + 1 + i)."""
    for i, (eng, orc) in enumerate(zip(engine_counters, oracle_counters)):
        for key in sorted(set(eng) | set(orc)):
            ev, ov = eng.get(key), orc.get(key)
            if ev != ov:
                return Divergence(start_tick + 1 + i, f"counters.{key}",
                                  ev, ov)
    return None


def scalar_divergence(name: str, engine_value, oracle_value,
                      tick: int) -> Optional[Divergence]:
    """End-of-run scalar comparison (config ids, final memberships)."""
    if engine_value != oracle_value:
        return Divergence(tick, name, engine_value, oracle_value)
    return None


def earliest(candidates: Sequence[Optional[Divergence]]) \
        -> Optional[Divergence]:
    """The divergence with the smallest tick; list order breaks ties."""
    found = [d for d in candidates if d is not None]
    if not found:
        return None
    best = found[0]
    for d in found[1:]:
        if d.tick < best.tick:
            best = d
    return best


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------


def build_report(div: Divergence,
                 engine_metrics: Optional[Sequence] = None,
                 oracle_metrics: Optional[Sequence] = None,
                 events: Sequence = (),
                 context_n: int = 4) -> DivergenceReport:
    """Attach the last ``context_n`` records at/before the divergence tick.

    Context records are tagged dicts: TickMetrics rows from each supplied
    stream (``"record": "tick_metrics"``) and ViewEvents
    (``"record": "view_event"``), all with tick <= the divergence tick.
    """
    context: List[Dict[str, object]] = []
    for stream in (engine_metrics, oracle_metrics):
        if not stream:
            continue
        rows = [m for m in stream if m.tick <= div.tick][-context_n:]
        for m in rows:
            rec = {"record": "tick_metrics"}
            rec.update(m.as_dict())
            context.append(rec)
    for e in [e for e in events if e.tick <= div.tick][-context_n:]:
        rec = {"record": "view_event"}
        rec.update(e.as_dict() if hasattr(e, "as_dict")
                   else dataclasses.asdict(e))
        rec["slots"] = _jsonable(rec.get("slots"))
        context.append(rec)
    return DivergenceReport(tick=div.tick, field=div.field,
                            engine=div.engine, oracle=div.oracle,
                            context=context)
