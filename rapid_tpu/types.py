"""Core wire/protocol types.

The reference defines these in protobuf (rapid/src/main/proto/rapid.proto):
``Endpoint`` (:13-17), ``NodeId`` (:50-54), ``EdgeStatus`` / alert messages
(:95-115), the join protocol (:57-91) and consensus messages (:124-169).
Here they are plain immutable Python dataclasses: the oracle passes them
in-process, and the kernel engine lowers them to integer tensors (slot ids +
64-bit hashes) — there is no RPC wire format to serialize for.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True, order=True)
class Endpoint:
    """A node address. Reference: rapid.proto:13-17 (hostname bytes + port)."""

    hostname: str
    port: int

    def __str__(self) -> str:
        return f"{self.hostname}:{self.port}"

    @staticmethod
    def parse(s: str) -> "Endpoint":
        host, _, port = s.rpartition(":")
        if not host or not port:
            raise ValueError(f"malformed endpoint: {s!r}")
        return Endpoint(host, int(port))


@dataclass(frozen=True, order=True)
class NodeId:
    """A 128-bit logical node identifier. Reference: rapid.proto:50-54.

    The reference orders NodeIds by (high, low) (MembershipView.java:474-500);
    dataclass ordering on (high, low) reproduces that.
    """

    high: int
    low: int


class EdgeStatus(enum.Enum):
    UP = 0
    DOWN = 1


class JoinStatusCode(enum.Enum):
    """Reference: rapid.proto:85-91."""

    HOSTNAME_ALREADY_IN_RING = 0
    UUID_ALREADY_IN_RING = 1
    SAFE_TO_JOIN = 2
    CONFIG_CHANGED = 3
    MEMBERSHIP_REJECTED = 4


Metadata = Dict[str, bytes]


# ---------------------------------------------------------------------------
# Protocol messages (the RapidRequest oneof, rapid.proto:21-45)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PreJoinMessage:
    """Join phase 1, joiner -> seed. Reference: rapid.proto:58-63."""

    sender: Endpoint
    node_id: NodeId


@dataclass(frozen=True)
class JoinMessage:
    """Join phase 2, joiner -> observer. Reference: rapid.proto:65-73."""

    sender: Endpoint
    node_id: NodeId
    configuration_id: int
    ring_numbers: Tuple[int, ...]
    metadata: Tuple[Tuple[str, bytes], ...] = ()


@dataclass(frozen=True)
class JoinResponse:
    """Reference: rapid.proto:75-84."""

    sender: Endpoint
    status_code: JoinStatusCode
    configuration_id: int
    endpoints: Tuple[Endpoint, ...] = ()
    identifiers: Tuple[NodeId, ...] = ()
    metadata: Tuple[Tuple[Endpoint, Tuple[Tuple[str, bytes], ...]], ...] = ()


@dataclass(frozen=True)
class AlertMessage:
    """An edge-status report. Reference: rapid.proto:99-110.

    ``node_id``/``metadata`` ride along only on UP (join) alerts.
    """

    edge_src: Endpoint
    edge_dst: Endpoint
    edge_status: EdgeStatus
    configuration_id: int
    ring_numbers: Tuple[int, ...]
    node_id: Optional[NodeId] = None
    metadata: Tuple[Tuple[str, bytes], ...] = ()


@dataclass(frozen=True)
class BatchedAlertMessage:
    """Reference: rapid.proto:112-115."""

    sender: Endpoint
    messages: Tuple[AlertMessage, ...]


@dataclass(frozen=True)
class FastRoundPhase2bMessage:
    """A fast-round vote. Reference: rapid.proto:124-129."""

    sender: Endpoint
    configuration_id: int
    endpoints: Tuple[Endpoint, ...]


@dataclass(frozen=True, order=True)
class Rank:
    """Classic-round rank (round, node_index). Reference: rapid.proto:133-136.

    Ordering is lexicographic (round, node_index), matching
    Paxos.java:333-339.
    """

    round: int
    node_index: int


@dataclass(frozen=True)
class Phase1aMessage:
    sender: Endpoint
    configuration_id: int
    rank: Rank


@dataclass(frozen=True)
class Phase1bMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    vrnd: Rank
    vval: Tuple[Endpoint, ...]


@dataclass(frozen=True)
class Phase2aMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    vval: Tuple[Endpoint, ...]


@dataclass(frozen=True)
class Phase2bMessage:
    sender: Endpoint
    configuration_id: int
    rnd: Rank
    endpoints: Tuple[Endpoint, ...]


@dataclass(frozen=True)
class LeaveMessage:
    """Reference: rapid.proto:185-188."""

    sender: Endpoint


@dataclass(frozen=True)
class ProbeMessage:
    sender: Endpoint


class ProbeStatus(enum.Enum):
    OK = 0
    BOOTSTRAPPING = 1


@dataclass(frozen=True)
class ProbeResponse:
    status: ProbeStatus = ProbeStatus.OK


@dataclass(frozen=True)
class Response:
    """Generic empty response (RapidResponse with no payload)."""


RapidRequest = (
    PreJoinMessage
    | JoinMessage
    | BatchedAlertMessage
    | FastRoundPhase2bMessage
    | Phase1aMessage
    | Phase1bMessage
    | Phase2aMessage
    | Phase2bMessage
    | LeaveMessage
    | ProbeMessage
)

CONSENSUS_MESSAGE_TYPES = (
    FastRoundPhase2bMessage,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
)
