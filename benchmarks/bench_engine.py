"""Benchmark the jitted tick engine: simulated-gossip-rounds/sec.

Two scenarios, selected with ``--scenario``:

- ``steady`` (default): an N-node crash-burst through
  ``rapid_tpu.engine.simulate`` — one jit-compiled ``lax.scan`` dispatch
  for the whole run.
- ``churn``: sustained membership churn via
  ``rapid_tpu.engine.churn.synthetic_churn_schedule`` — alternating
  join/leave bursts reconfigure the view inside the same scan.
- ``contested``: repeated split-vote consensus instances via
  ``rapid_tpu.engine.paxos.synthetic_contested_schedule`` — the fast
  round misses quorum every time and the classic-Paxos fallback kernel
  decides each view change.
- ``partition``: an asymmetric one-way partition through the fault
  adversary (``rapid_tpu.engine.adversary``) — enough slots isolated
  that the fast round misses quorum and the organic classic-Paxos
  fallback decides under the partition. This scenario runs the host
  discrete-event engine *and* the oracle and asserts bit-identity
  before reporting, so it is a correctness gate as much as a
  benchmark; it is O(n^2) per tick on the host, keep ``--n`` small
  (64-256).
- ``delay``: a latency-adversary campaign — every sampled member draws
  a delay-family scenario (fixed per-edge delay, bounded jitter with
  reordering, or slow-link asymmetry) paired with a crash burst, runs
  device-exact through the per-receiver delivery ring, and the payload
  reports per-regime ticks-to-first-decide tails
  (``campaign.delay_regimes``).

One *gossip round* is one failure-detector interval — the period in
which every node probes each unique subject once — i.e.
``fd_interval_ticks`` simulated ticks.

The BASELINE.json metric is rounds/sec at N=100k:

    JAX_PLATFORMS=cpu python benchmarks/bench_engine.py --n 100000

Emits one BENCH-style JSON object (with trailing newline) on stdout, or
to ``--out FILE`` when given.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np  # noqa: E402


def synthetic_uids(n: int, seed: int = 0) -> np.ndarray:
    """Distinct 64-bit node identities without hashing n hostnames."""
    from rapid_tpu import hashing

    hi, lo = hashing.np_to_limbs(np.arange(1, n + 1, dtype=np.uint64))
    hi, lo = hashing.hash64_limbs(np, hi, lo, seed=0xBEEF ^ (seed & 0xFFFF))
    return hashing.np_from_limbs(hi, lo)


def _telemetry_block(logs) -> dict:
    """Per-run protocol summary (RunSummary) from the engine's StepLog."""
    from rapid_tpu.telemetry.metrics import engine_metrics, summarize

    return summarize(engine_metrics(logs)).as_dict()


def _schema_version() -> int:
    from rapid_tpu.telemetry.schema import SCHEMA_VERSION

    return SCHEMA_VERSION


def run(n: int, ticks: int, crash_frac: float, crash_tick: int,
        settings, seed: int = 0, trace_writer=None) -> dict:
    import jax

    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.step import simulate
    from rapid_tpu.telemetry.trace import trace_from_logs, wall_span

    uids = synthetic_uids(n, seed)
    boot_start = time.perf_counter()
    with wall_span(trace_writer, "init_state+topology", {"n": n}):
        state = init_state(uids, id_fp_sum=0, settings=settings)
        jax.block_until_ready(state)
    boot_s = time.perf_counter() - boot_start

    n_crash = max(1, int(n * crash_frac))
    crash_ticks = [I32_MAX] * n
    for slot in range(0, n, max(1, n // n_crash)):
        crash_ticks[slot] = crash_tick
    faults = crash_faults(crash_ticks)

    # First call compiles (trace + XLA); second call measures steady state.
    compile_start = time.perf_counter()
    with wall_span(trace_writer, "jit_trace+compile", {"ticks": ticks}):
        final, logs = simulate(state, faults, ticks, settings)
        jax.block_until_ready((final, logs))
    compile_s = time.perf_counter() - compile_start

    run_start = time.perf_counter()
    with wall_span(trace_writer, "device_dispatch", {"ticks": ticks}):
        final, logs = simulate(state, faults, ticks, settings)
        jax.block_until_ready((final, logs))
    wall_s = time.perf_counter() - run_start

    if trace_writer is not None:
        trace_from_logs(logs, settings, writer=trace_writer)

    telemetry = _telemetry_block(logs)
    decisions = int(np.asarray(logs.decide_now).sum())
    announces = int(np.asarray(logs.announce_now).sum())
    ticks_per_sec = ticks / wall_s
    return {
        "bench": "engine_tick",
        "schema_version": _schema_version(),
        "platform": jax.default_backend(),
        "n": n,
        "k": settings.K,
        "ticks": ticks,
        "crashed_nodes": int(np.sum(np.asarray(crash_ticks) != I32_MAX)),
        "boot_s": round(boot_s, 4),
        "compile_s": round(compile_s, 4),
        "wall_s": round(wall_s, 4),
        "ticks_per_sec": round(ticks_per_sec, 2),
        "rounds_per_sec": round(ticks_per_sec / settings.fd_interval_ticks, 2),
        "announcements": announces,
        "decisions": decisions,
        "final_members": int(np.asarray(final.member).sum()),
        "ticks_to_first_decide": telemetry["ticks_to_first_decide"],
        "messages_per_view_change": telemetry["messages_per_view_change"],
        "telemetry": telemetry,
    }


def run_churn(n: int, ticks: int, burst: int, settings, seed: int = 0,
              trace_writer=None) -> dict:
    """Sustained join/leave churn: membership oscillates between ``n`` and
    ``n + burst`` while the jitted scan reconfigures the view on every
    decided proposal."""
    import jax

    from rapid_tpu.engine.churn import synthetic_churn_schedule
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.step import simulate
    from rapid_tpu.telemetry.trace import trace_from_logs, wall_span

    period = settings.churn_decide_delay_ticks + 3
    start = 10
    cycles = max(1, (ticks - start) // (2 * period))
    capacity = n + cycles * burst
    uids = synthetic_uids(capacity, seed)
    member = np.zeros(capacity, bool)
    member[:n] = True

    with wall_span(trace_writer, "plan_churn",
                   {"capacity": capacity, "burst": burst}):
        schedule, id_fps, info = synthetic_churn_schedule(
            capacity, n, settings, start=start, burst=burst, period=period)

    boot_start = time.perf_counter()
    with wall_span(trace_writer, "init_state+topology",
                   {"n": n, "capacity": capacity}):
        state = init_state(uids, id_fp_sum=0, settings=settings,
                           member=member, id_fps=id_fps)
        jax.block_until_ready(state)
    boot_s = time.perf_counter() - boot_start

    faults = crash_faults([I32_MAX] * capacity)

    compile_start = time.perf_counter()
    with wall_span(trace_writer, "jit_trace+compile", {"ticks": ticks}):
        final, logs = simulate(state, faults, ticks, settings, churn=schedule)
        jax.block_until_ready((final, logs))
    compile_s = time.perf_counter() - compile_start

    run_start = time.perf_counter()
    with wall_span(trace_writer, "device_dispatch", {"ticks": ticks}):
        final, logs = simulate(state, faults, ticks, settings, churn=schedule)
        jax.block_until_ready((final, logs))
    wall_s = time.perf_counter() - run_start

    if trace_writer is not None:
        trace_from_logs(logs, settings, writer=trace_writer)

    telemetry = _telemetry_block(logs)
    decisions = int(np.asarray(logs.decide_now).sum())
    ticks_per_sec = ticks / wall_s
    return {
        "bench": "engine_tick",
        "schema_version": _schema_version(),
        "scenario": "churn",
        "platform": jax.default_backend(),
        "n": n,
        "capacity": capacity,
        "k": settings.K,
        "ticks": ticks,
        "churn_bursts": info["bursts"],
        "burst_size": info["burst_size"],
        "boot_s": round(boot_s, 4),
        "compile_s": round(compile_s, 4),
        "wall_s": round(wall_s, 4),
        "ticks_per_sec": round(ticks_per_sec, 2),
        "rounds_per_sec": round(ticks_per_sec / settings.fd_interval_ticks, 2),
        "decisions": decisions,
        "final_members": int(np.asarray(final.member).sum()),
        "ticks_to_first_decide": telemetry["ticks_to_first_decide"],
        "messages_per_view_change": telemetry["messages_per_view_change"],
        "telemetry": telemetry,
    }


def run_contested(n: int, ticks: int, settings, seed: int = 0,
                  trace_writer=None) -> dict:
    """Contested consensus: every scripted instance splits the members
    into two camps below the fast quorum, so the classic-Paxos fallback
    kernel (``rapid_tpu.engine.paxos``) decides each view change."""
    import jax

    from rapid_tpu.engine.paxos import synthetic_contested_schedule
    from rapid_tpu.engine.state import I32_MAX, crash_faults, init_state
    from rapid_tpu.engine.step import simulate
    from rapid_tpu.telemetry.trace import trace_from_logs, wall_span

    uids = synthetic_uids(n, seed)
    with wall_span(trace_writer, "plan_fallback", {"n": n}):
        schedule, info = synthetic_contested_schedule(
            n, settings, ticks, uids=uids)

    boot_start = time.perf_counter()
    with wall_span(trace_writer, "init_state+topology", {"n": n}):
        state = init_state(uids, id_fp_sum=0, settings=settings)
        jax.block_until_ready(state)
    boot_s = time.perf_counter() - boot_start

    faults = crash_faults([I32_MAX] * n)

    compile_start = time.perf_counter()
    with wall_span(trace_writer, "jit_trace+compile", {"ticks": ticks}):
        final, logs = simulate(state, faults, ticks, settings,
                               fallback=schedule)
        jax.block_until_ready((final, logs))
    compile_s = time.perf_counter() - compile_start

    run_start = time.perf_counter()
    with wall_span(trace_writer, "device_dispatch", {"ticks": ticks}):
        final, logs = simulate(state, faults, ticks, settings,
                               fallback=schedule)
        jax.block_until_ready((final, logs))
    wall_s = time.perf_counter() - run_start

    if trace_writer is not None:
        trace_from_logs(logs, settings, writer=trace_writer)

    telemetry = _telemetry_block(logs)
    decisions = int(np.asarray(logs.decide_now).sum())
    ticks_per_sec = ticks / wall_s
    return {
        "bench": "engine_tick",
        "schema_version": _schema_version(),
        "scenario": "contested",
        "platform": jax.default_backend(),
        "n": n,
        "k": settings.K,
        "ticks": ticks,
        "contested_instances": info["instances"],
        "boot_s": round(boot_s, 4),
        "compile_s": round(compile_s, 4),
        "wall_s": round(wall_s, 4),
        "ticks_per_sec": round(ticks_per_sec, 2),
        "rounds_per_sec": round(ticks_per_sec / settings.fd_interval_ticks, 2),
        "decisions": decisions,
        "final_members": int(np.asarray(final.member).sum()),
        "ticks_to_first_decide": telemetry["ticks_to_first_decide"],
        "messages_per_view_change": telemetry["messages_per_view_change"],
        "telemetry": telemetry,
    }


def run_partition(n: int, ticks: int, settings, seed: int = 0,
                  iso_frac: float = 0.3) -> dict:
    """Asymmetric one-way partition through the on-device fault
    adversary: the last ``iso_frac`` of the slot range is isolated
    one-way (rest->iso blocked), so the reachable side detects the
    isolated slots but its fast votes fall short of the fast quorum and
    the organic jittered classic-Paxos fallback decides the removal
    under the partition. The run is a full adversarial differential —
    counts are reported only after the engine is proven bit-identical
    to the oracle."""
    from rapid_tpu.engine.diff import run_adversarial_differential
    from rapid_tpu.faults import AdversarySchedule, LinkWindow
    from rapid_tpu.telemetry.metrics import summarize

    # iso > (n-1)//4 guarantees the fast quorum n-(n-1)//4 is missed
    # while the classic majority n//2+1 stays reachable.
    n_iso = max((n - 1) // 4 + 1, int(round(n * iso_frac)))
    iso = frozenset(range(n - n_iso, n))
    rest = frozenset(range(n)) - iso
    schedule = AdversarySchedule(
        n=n,
        windows=(LinkWindow(src_slots=rest, dst_slots=iso, start_tick=3),),
        seed=seed)

    run_start = time.perf_counter()
    res = run_adversarial_differential(schedule, ticks, settings)
    wall_s = time.perf_counter() - run_start
    res.assert_identical()

    telemetry = summarize(res.engine_metrics).as_dict()
    survivor = min(rest)
    removed = {s for ev in res.engine_events_by_slot[survivor]
               if ev.kind == "view_change" for s in ev.slots}
    ticks_per_sec = ticks / wall_s
    return {
        "bench": "engine_tick",
        "schema_version": _schema_version(),
        "scenario": "partition",
        "platform": "host",
        "n": n,
        "k": settings.K,
        "ticks": ticks,
        "isolated_slots": n_iso,
        "window_start_tick": 3,
        "boot_s": 0.0,
        "compile_s": 0.0,
        "wall_s": round(wall_s, 4),
        "ticks_per_sec": round(ticks_per_sec, 2),
        "rounds_per_sec": round(ticks_per_sec / settings.fd_interval_ticks, 2),
        "announcements": telemetry["announcements"],
        "decisions": telemetry["decisions"],
        "final_members": n - len(removed),
        "ticks_to_first_decide": telemetry["ticks_to_first_decide"],
        "messages_per_view_change": telemetry["messages_per_view_change"],
        "telemetry": telemetry,
    }


def run_delay(clusters: int, n: int, ticks: int, settings, seed: int = 0,
              fleet_size: int = None, spot_checks: int = 0) -> dict:
    """Latency-adversary campaign: every sampled member draws from the
    delay family only (fixed per-edge delay, bounded jitter with
    reordering, slow-link asymmetry), each paired with a crash burst so
    the member decides a view change *under* latency. All members run
    device-exact through the per-receiver delivery ring; the payload's
    ``campaign.delay_regimes`` block reports the nearest-rank
    ticks-to-first-decide tail per regime — the committed baseline gates
    those tails exactly (``scripts/bench_compare.py``)."""
    from rapid_tpu.campaign import CampaignConfig, run_campaign
    from rapid_tpu.faults import ScenarioWeights

    weights = ScenarioWeights(crash=0.0, partition=0.0, flip_flop=0.0,
                              contested=0.0, churn=0.0,
                              delay=1.0, jitter=1.0, slow_asym=1.0)
    cfg = CampaignConfig(clusters=clusters, n=n, ticks=ticks, seed=seed,
                         fleet_size=fleet_size or clusters,
                         weights=weights, spot_checks=spot_checks,
                         settings=settings)
    payload = run_campaign(cfg)
    payload["scenario"] = "delay"
    return payload


def run_streaming(n: int, capacity: int, ticks: int, chunk_ticks: int,
                  settings, seed: int = 0) -> dict:
    """Streaming service entry: the resident engine under open-loop
    traffic (Poisson joins, correlated leave bursts, a diurnal wave),
    run as donated double-buffered ``stream_chunk_ticks`` scan segments
    with one mid-run checkpoint save/restore round trip
    (``ResidentEngine.verify_round_trip`` — the payload's ``checkpoint``
    block carries the bit-exactness verdicts, and the baseline gates
    them exactly). Event counts, protocol totals, the decide-latency
    tail, and the traffic config are deterministic in ``seed``; the
    events/sec figure is the wall-clock rate the stream sustained."""
    import dataclasses
    import tempfile

    import jax

    from rapid_tpu.campaign import _rate
    from rapid_tpu.service import TrafficConfig, boot_resident
    from rapid_tpu.telemetry.metrics import summarize

    settings = dataclasses.replace(settings,
                                   stream_chunk_ticks=chunk_ticks)
    traffic = TrafficConfig(seed=seed, diurnal_amplitude=0.3,
                            diurnal_period_ticks=max(256, ticks // 4))
    n_chunks = max(2, -(-ticks // chunk_ticks))
    eng = boot_resident(settings, capacity, n, seed=seed,
                        traffic_config=traffic)
    run_start = time.perf_counter()
    first = n_chunks // 2
    eng.run(first)
    with tempfile.TemporaryDirectory(prefix="rapid_stream_ck_") as ckdir:
        eng.verify_round_trip(os.path.join(ckdir, "ck"))
    eng.run(n_chunks - first - 1)
    wall_s = time.perf_counter() - run_start
    summary = eng.summary()
    eng.close()

    telemetry = summarize(eng.metrics).as_dict()
    ticks_per_sec = summary["ticks"] / wall_s
    return {
        "bench": "engine_tick",
        "schema_version": _schema_version(),
        "scenario": "streaming",
        "platform": jax.default_backend(),
        "n": n,
        "capacity": capacity,
        "k": settings.K,
        "ticks": summary["ticks"],
        "chunk_ticks": chunk_ticks,
        "chunks": summary["chunks"],
        "events_injected": summary["events_injected"],
        "joins": summary["joins"],
        "leaves": summary["leaves"],
        "bursts": summary["bursts"],
        "wall_s": round(wall_s, 4),
        "ticks_per_sec": round(ticks_per_sec, 2),
        "rounds_per_sec": round(
            ticks_per_sec / settings.fd_interval_ticks, 2),
        "events_per_sec": _rate(summary["events_injected"], wall_s),
        "announcements": telemetry["announcements"],
        "decisions": telemetry["decisions"],
        "final_members": int(np.asarray(eng.state.member).sum()),
        "ticks_to_first_decide": telemetry["ticks_to_first_decide"],
        "messages_per_view_change": telemetry["messages_per_view_change"],
        "ticks_to_view_change": summary["ticks_to_view_change"],
        "lineage": summary["lineage"],
        "traffic": summary["traffic"],
        "checkpoint": summary["checkpoint"],
        "live_buffer_bytes": summary["live_buffer_bytes"],
        "telemetry": telemetry,
    }


def run_fleet(clusters: int, n: int, ticks: int, settings, seed: int = 0,
              fleet_size: int = None, spot_checks: int = 0) -> dict:
    """Monte-Carlo fleet campaign: ``clusters`` sampled fault/churn
    scenarios vmapped over a leading fleet axis, ``fleet_size`` clusters
    per jitted dispatch (``rapid_tpu.campaign``). The payload is an
    ``engine_tick`` run whose ``telemetry`` is the fleet-merged
    RunSummary plus the ``campaign`` distributions block; with
    ``spot_checks > 0`` a seeded member subset is replayed through the
    host oracle referee and the run dies on any per-slot divergence."""
    from rapid_tpu.campaign import CampaignConfig, run_campaign

    cfg = CampaignConfig(clusters=clusters, n=n, ticks=ticks, seed=seed,
                         fleet_size=fleet_size or clusters,
                         spot_checks=spot_checks, settings=settings)
    return run_campaign(cfg)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--n", type=int, default=10_000,
                        help="simulated cluster size (default 10k)")
    parser.add_argument("--ticks", type=int, default=50,
                        help="simulated ticks per run (default 50)")
    parser.add_argument("--k", type=int, default=10, help="rings (default 10)")
    parser.add_argument("--crash-frac", type=float, default=0.01,
                        help="fraction of nodes crashing (default 1%%)")
    parser.add_argument("--crash-tick", type=int, default=5,
                        help="tick of the correlated crash burst")
    parser.add_argument("--scenario",
                        choices=("steady", "churn", "contested",
                                 "partition", "delay", "streaming",
                                 "fleet"),
                        default="steady",
                        help="steady crash-burst, sustained join/leave "
                             "churn, contested consensus through the "
                             "classic-Paxos fallback, a one-way "
                             "partition through the fault adversary "
                             "(host-side differential; keep --n small "
                             "and --ticks >= 250), a latency-adversary "
                             "campaign over the delay/jitter/slow-asym "
                             "family (per-receiver delivery ring, "
                             "per-regime decide tails), a resident "
                             "streaming run under open-loop traffic "
                             "with a mid-run checkpoint round trip, or "
                             "a vmapped Monte-Carlo fleet campaign over "
                             "sampled scenarios (default steady)")
    parser.add_argument("--clusters", type=int, default=64,
                        help="fleet scenario: sampled clusters")
    parser.add_argument("--fleet-size", type=int, default=None,
                        help="fleet scenario: clusters per dispatch "
                             "(default: all in one dispatch)")
    parser.add_argument("--spot-checks", type=int, default=0,
                        help="fleet scenario: members replayed through "
                             "the host oracle referee")
    parser.add_argument("--burst", type=int, default=8,
                        help="churn scenario: slots per join/leave burst")
    parser.add_argument("--capacity", type=int, default=None,
                        help="streaming scenario: slot universe "
                             "(default 4 * n)")
    parser.add_argument("--chunk", type=int, default=256,
                        help="streaming scenario: "
                             "Settings.stream_chunk_ticks")
    parser.add_argument("--seed", type=int, default=0,
                        help="perturbs the synthetic node identities")
    parser.add_argument("--out", type=str, default=None,
                        help="write the JSON artifact to FILE (default: "
                             "stdout)")
    parser.add_argument("--sweep", action="store_true",
                        help="run the BASELINE sweep n in {1k, 10k, 100k}")
    parser.add_argument("--profile-sweep", action="store_true",
                        help="per-kernel cost observatory: lower each tick "
                             "sub-kernel separately and emit the dominance "
                             "report (rapid_tpu.telemetry.profile)")
    parser.add_argument("--profile-sizes", type=int, nargs="+",
                        default=[1_000, 10_000, 100_000], metavar="N",
                        help="cluster sizes for --profile-sweep "
                             "(default 1k 10k 100k)")
    parser.add_argument("--profile-repeats", type=int, default=5,
                        help="timed dispatches per kernel in "
                             "--profile-sweep (default 5)")
    parser.add_argument("--variant-sizes", type=int, nargs="+",
                        default=None, metavar="N",
                        help="--profile-sweep extra: profile the "
                             "ring-variant aggregation kernel vs the "
                             "dense broadcast at these sizes (dense "
                             "sizes over the memory budget become "
                             "documented refusals)")
    parser.add_argument("--trace", type=str, default=None, metavar="FILE",
                        help="write a Chrome/Perfetto trace-event JSON of "
                             "the measured run (open at ui.perfetto.dev)")
    parser.add_argument("--jax-profile", type=str, default=None,
                        metavar="DIR",
                        help="also capture a jax.profiler trace into DIR "
                             "(TensorBoard/Perfetto-compatible)")
    args = parser.parse_args(argv)

    if args.trace and args.sweep:
        parser.error("--trace records one run; combine with --n, not --sweep")

    from rapid_tpu.settings import Settings
    from rapid_tpu.telemetry.trace import TraceWriter, jax_profiler_trace

    settings = Settings(K=args.k)

    if args.profile_sweep:
        from rapid_tpu.telemetry.profile import dominance_report

        report = dominance_report(args.profile_sizes, settings,
                                  repeats=args.profile_repeats,
                                  seed=args.seed,
                                  variant_sizes=args.variant_sizes)
        if args.out:
            from rapid_tpu.telemetry import write_json_artifact

            write_json_artifact(args.out, report, indent=2)
        else:
            sys.stdout.write(json.dumps(report) + "\n")
            sys.stdout.flush()
        return 0
    writer = TraceWriter() if args.trace else None
    sizes = [1_000, 10_000, 100_000] if args.sweep else [args.n]
    with jax_profiler_trace(args.jax_profile):
        if args.scenario == "churn":
            results = [run_churn(n, args.ticks, args.burst, settings,
                                 args.seed, trace_writer=writer)
                       for n in sizes]
        elif args.scenario == "contested":
            results = [run_contested(n, args.ticks, settings, args.seed,
                                     trace_writer=writer)
                       for n in sizes]
        elif args.scenario == "partition":
            if writer is not None:
                parser.error("--trace records jitted runs; the partition "
                             "scenario is a host-side differential")
            results = [run_partition(n, args.ticks, settings, args.seed)
                       for n in sizes]
        elif args.scenario == "delay":
            if writer is not None:
                parser.error("--trace records one cluster's logs; use "
                             "python -m rapid_tpu.campaign for fleets")
            results = [run_delay(args.clusters, n, args.ticks, settings,
                                 args.seed, fleet_size=args.fleet_size,
                                 spot_checks=args.spot_checks)
                       for n in sizes]
        elif args.scenario == "streaming":
            if writer is not None:
                parser.error("--trace records one jitted run; the "
                             "streaming scenario is a chunked stream")
            results = [run_streaming(n, args.capacity or 4 * n,
                                     args.ticks, args.chunk, settings,
                                     args.seed)
                       for n in sizes]
        elif args.scenario == "fleet":
            if writer is not None:
                parser.error("--trace records one cluster's logs; use "
                             "python -m rapid_tpu.campaign for fleets")
            results = [run_fleet(args.clusters, n, args.ticks, settings,
                                 args.seed, fleet_size=args.fleet_size,
                                 spot_checks=args.spot_checks)
                       for n in sizes]
        else:
            results = [run(n, args.ticks, args.crash_frac, args.crash_tick,
                           settings, args.seed, trace_writer=writer)
                       for n in sizes]
    payload = results[0] if len(results) == 1 else {
        "bench": "engine_tick",
        "schema_version": _schema_version(),
        "sweep": results}
    if writer is not None:
        writer.write(args.trace)
        payload["trace"] = args.trace
    # BENCH artifacts end with a newline (telemetry.write_json_artifact
    # is the chokepoint). On stdout the payload is one compact line, so
    # harnesses that parse the last stdout line always get the whole
    # JSON object.
    if args.out:
        from rapid_tpu.telemetry import write_json_artifact

        write_json_artifact(args.out, payload, indent=2)
    else:
        sys.stdout.write(json.dumps(payload) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
