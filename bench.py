#!/usr/bin/env python
"""Repo-root benchmark shim: one small steady + churn suite, JSON out.

This is the harness entry point (``python bench.py``): it runs the
engine tick benchmark twice — an N=1k steady crash-burst and an N=1k
sustained-churn run — with defaults small enough to finish quickly on
CPU, and emits a single ``engine_tick_suite`` JSON payload (with
trailing newline) on stdout or to ``--out FILE``. Each sub-payload
carries the per-run protocol summary in its ``telemetry`` block
(``rapid_tpu.telemetry.metrics.RunSummary``), validatable with::

    python -m rapid_tpu.telemetry.schema BENCH.json

For sweeps, tracing, and scenario knobs use the full benchmark:
``python benchmarks/bench_engine.py --help``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from benchmarks.bench_engine import run, run_churn  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--n", type=int, default=1_000,
                        help="simulated cluster size (default 1k)")
    parser.add_argument("--ticks", type=int, default=120,
                        help="simulated ticks per run (default 120)")
    parser.add_argument("--burst", type=int, default=8,
                        help="churn run: slots per join/leave burst")
    parser.add_argument("--seed", type=int, default=0,
                        help="perturbs the synthetic node identities")
    parser.add_argument("--out", type=str, default=None,
                        help="write the JSON artifact to FILE "
                             "(default: stdout)")
    args = parser.parse_args(argv)

    from rapid_tpu.settings import Settings

    settings = Settings()
    payload = {
        "bench": "engine_tick_suite",
        "n": args.n,
        "ticks": args.ticks,
        "steady": run(args.n, args.ticks, crash_frac=0.01, crash_tick=5,
                      settings=settings, seed=args.seed),
        "churn": run_churn(args.n, args.ticks, args.burst, settings,
                           args.seed),
    }
    text = json.dumps(payload, indent=2) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
