#!/usr/bin/env python
"""Repo-root benchmark shim: steady + churn + contested + partition
+ delay + streaming + fleet suite, JSON out.

This is the harness entry point (``python bench.py``): it runs the
engine tick benchmark seven times — an N=256 steady crash-burst, an
N=256 sustained-churn run, an N=256 contested-consensus run through
the classic-Paxos fallback kernel, a small one-way-partition run
through the fault adversary (a host-side oracle differential, so it
uses its own ``--partition-n`` size), a latency-adversary ``delay``
campaign (every member draws from the delay/jitter/slow-asym family,
runs device-exact through the per-receiver delivery ring, and the
payload's ``campaign.delay_regimes`` block carries per-regime
ticks-to-first-decide tails), a ``streaming`` resident-service run
(open-loop Poisson/burst/diurnal traffic lowered chunk-by-chunk into
the donated ``stream_chunk_ticks`` scan, with one mid-run checkpoint
save/restore round trip whose bit-exactness verdicts the payload
carries; see ``rapid_tpu/service/``), and a deterministic Monte-Carlo
``fleet`` campaign (``--fleet-clusters`` N=``--fleet-n`` clusters with
a mixed fault/churn sample, vmapped ``--fleet-size`` clusters per
dispatch so the committed payload carries a multi-dispatch timeline;
see ``rapid_tpu/campaign.py``) — with defaults small enough to finish
quickly on CPU, and emits a single ``engine_tick_suite`` JSON payload.

The stdout payload is always one compact *summary-only* line (the last
line, explicitly flushed, so harnesses that parse the stdout tail always
get the whole object): the per-view-change row lists are elided down to
a ``view_changes_elided`` count, keeping the line small no matter how
many view changes the run decided. The full payload — per-view-change
rows included — goes to ``--out FILE`` (indented). Each sub-payload
carries the per-run protocol summary in its ``telemetry`` block
(``rapid_tpu.telemetry.metrics.RunSummary``); both forms validate with::

Wall-budget discipline: a bare ``python bench.py`` must finish inside a
capture harness's budget and must leave a parseable stdout tail even
when it doesn't. The defaults therefore match the tier-1 regression
config (N=256 — the config ``scripts/tier1.sh`` proves out every run);
``--fast`` shrinks every knob further for smoke use. Entries run one at
a time with a stderr progress line each, and the final stdout line is
emitted from a ``finally`` block with a SIGTERM handler installed — a
budget kill (``timeout``'s TERM, before the KILL escalation) still
flushes a payload carrying the completed entries plus a ``partial``
block naming what was cut and why (exit 1, and schema validation fails
loudly on the missing entries — a partial record is diagnosable, an
empty tail is not).

    python -m rapid_tpu.telemetry.schema BENCH.json

``scripts/bench_compare.py`` diffs the ``--out`` artifact against the
committed ``benchmarks/baseline.json`` regression baseline.

For sweeps, tracing, and scenario knobs use the full benchmark:
``python benchmarks/bench_engine.py --help``.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from benchmarks.bench_engine import (  # noqa: E402
    run,
    run_churn,
    run_contested,
    run_delay,
    run_fleet,
    run_partition,
    run_streaming,
)


#: Suite entries in run order (heaviest last, so a budget cut keeps the
#: cheap protocol entries).
SUITE_ENTRIES = ("steady", "churn", "contested", "partition", "delay",
                 "streaming", "fleet")

#: ``--fast`` preset: every knob shrunk to smoke scale. Applied only to
#: knobs the caller left at their defaults, so ``--fast --n 512`` still
#: honors the explicit 512.
FAST_PRESET = {
    "n": 128, "ticks": 96, "partition_n": 32, "partition_ticks": 200,
    "delay_clusters": 4, "delay_n": 32, "delay_ticks": 160,
    "streaming_n": 16, "streaming_capacity": 48,
    "streaming_ticks": 1024, "streaming_chunk": 128,
    "fleet_clusters": 16, "fleet_size": 8, "fleet_n": 32,
    "fleet_ticks": 96,
}


class _BudgetCut(Exception):
    """Raised by the SIGTERM/SIGINT handler: the harness wall budget
    expired mid-suite and wants us gone — flush what we have."""


def _compact_payload(payload: dict) -> dict:
    """Summary-only form for the stdout line.

    The per-view-change rows are the only unbounded part of the payload
    (one record per decided proposal); eliding them — with an explicit
    ``view_changes_elided`` count so their absence is visible — keeps the
    last stdout line compact for tail-capture harnesses. The ``--out``
    artifact keeps the full rows. Entries a partial run never reached
    are simply absent.
    """
    out = dict(payload)
    for key in SUITE_ENTRIES:
        if key not in out:
            continue
        run_p = dict(out[key])
        tel = dict(run_p["telemetry"])
        tel["view_changes_elided"] = len(tel.get("view_changes") or [])
        tel["view_changes"] = []
        run_p["telemetry"] = tel
        out[key] = run_p
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--n", type=int, default=256,
                        help="simulated cluster size (default 256 — the "
                             "tier-1 regression config, sized to finish "
                             "a bare run inside a capture harness's "
                             "wall budget)")
    parser.add_argument("--fast", action="store_true",
                        help="smoke preset: shrink every knob still at "
                             "its default to smoke scale "
                             f"({FAST_PRESET})")
    parser.add_argument("--ticks", type=int, default=120,
                        help="simulated ticks per run (default 120)")
    parser.add_argument("--burst", type=int, default=8,
                        help="churn run: slots per join/leave burst")
    parser.add_argument("--seed", type=int, default=0,
                        help="perturbs the synthetic node identities")
    parser.add_argument("--partition-n", type=int, default=64,
                        help="cluster size for the partition run (a "
                             "host-side adversary differential, O(n^2) "
                             "per tick; default 64)")
    parser.add_argument("--partition-ticks", type=int, default=300,
                        help="ticks for the partition run (needs to "
                             "cover FD saturation plus the classic "
                             "fallback round; default 300)")
    parser.add_argument("--delay-clusters", type=int, default=16,
                        help="clusters in the delay campaign entry "
                             "(latency family only, all per-receiver "
                             "so quadratic state; default 16)")
    parser.add_argument("--delay-n", type=int, default=48,
                        help="members per delay-campaign cluster "
                             "(default 48)")
    parser.add_argument("--delay-ticks", type=int, default=240,
                        help="ticks per delay-campaign cluster (covers "
                             "FD saturation plus a delayed view change; "
                             "default 240)")
    parser.add_argument("--streaming-n", type=int, default=24,
                        help="initial members for the streaming entry "
                             "(default 24)")
    parser.add_argument("--streaming-capacity", type=int, default=96,
                        help="slot universe for the streaming entry "
                             "(members + joiner pool; default 96)")
    parser.add_argument("--streaming-ticks", type=int, default=3072,
                        help="total streamed ticks (chunked; covers "
                             "several traffic bursts plus the mid-run "
                             "checkpoint round trip; default 3072)")
    parser.add_argument("--streaming-chunk", type=int, default=256,
                        help="Settings.stream_chunk_ticks for the "
                             "streaming entry (default 256)")
    parser.add_argument("--fleet-clusters", type=int, default=128,
                        help="clusters in the fleet campaign entry "
                             "(default 128: two shared dispatches of "
                             "--fleet-size, so the dispatch timeline "
                             "shows the compile-vs-cache-hit split)")
    parser.add_argument("--fleet-size", type=int, default=64,
                        help="clusters per jitted fleet dispatch "
                             "(default 64)")
    parser.add_argument("--fleet-n", type=int, default=64,
                        help="members per fleet cluster (default 64)")
    parser.add_argument("--fleet-ticks", type=int, default=120,
                        help="ticks per fleet cluster (covers FD "
                             "saturation and partitions healing at "
                             "half run; default 120)")
    parser.add_argument("--out", type=str, default=None,
                        help="write the JSON artifact to FILE "
                             "(default: stdout)")
    args = parser.parse_args(argv)
    if args.fast:
        for knob, value in FAST_PRESET.items():
            if getattr(args, knob) == parser.get_default(knob):
                setattr(args, knob, value)

    from rapid_tpu.engine.fleet import enable_compile_cache
    from rapid_tpu.settings import Settings
    from rapid_tpu.telemetry.schema import SCHEMA_VERSION

    # Before the first compile: XLA's persistent cache binds at the
    # process's first compilation, so enabling it here covers every
    # suite entry (the campaign entries re-enable idempotently).
    enable_compile_cache()

    settings = Settings()
    entries = {
        "steady": lambda: run(args.n, args.ticks, crash_frac=0.01,
                              crash_tick=5, settings=settings,
                              seed=args.seed),
        "churn": lambda: run_churn(args.n, args.ticks, args.burst,
                                   settings, args.seed),
        "contested": lambda: run_contested(args.n, args.ticks, settings,
                                           args.seed),
        "partition": lambda: run_partition(args.partition_n,
                                           args.partition_ticks,
                                           settings, args.seed),
        "delay": lambda: run_delay(args.delay_clusters, args.delay_n,
                                   args.delay_ticks, settings, args.seed,
                                   fleet_size=args.delay_clusters),
        "streaming": lambda: run_streaming(args.streaming_n,
                                           args.streaming_capacity,
                                           args.streaming_ticks,
                                           args.streaming_chunk,
                                           settings, args.seed),
        "fleet": lambda: run_fleet(args.fleet_clusters, args.fleet_n,
                                   args.fleet_ticks, settings, args.seed,
                                   fleet_size=args.fleet_size),
    }
    payload = {
        "bench": "engine_tick_suite",
        "schema_version": SCHEMA_VERSION,
        "n": args.n,
        "ticks": args.ticks,
    }

    def _cut(signum, frame):
        raise _BudgetCut(signal.Signals(signum).name)

    prev = {sig: signal.signal(sig, _cut)
            for sig in (signal.SIGTERM, signal.SIGINT)}
    partial = None
    try:
        for name in SUITE_ENTRIES:
            t0 = time.perf_counter()
            payload[name] = entries[name]()
            print(f"bench: {name} done in "
                  f"{time.perf_counter() - t0:.1f}s", file=sys.stderr,
                  flush=True)
    except Exception as err:  # flush what we have, then exit nonzero
        done = [name for name in SUITE_ENTRIES if name in payload]
        partial = {"completed": done,
                   "missing": [name for name in SUITE_ENTRIES
                               if name not in payload],
                   "error": f"{type(err).__name__}: {err}"}
        payload["partial"] = partial
        print(f"bench: PARTIAL after {done} ({partial['error']})",
              file=sys.stderr, flush=True)
    finally:
        for sig, handler in prev.items():
            signal.signal(sig, handler)
        if args.out:
            from rapid_tpu.telemetry import write_json_artifact

            write_json_artifact(args.out, payload, indent=2)
        # The compact summary line always goes to stdout (flushed) so
        # the harness's tail-capture works whether or not --out was
        # given — on a budget cut it carries whatever completed.
        sys.stdout.write(
            json.dumps(_compact_payload(payload),
                       separators=(",", ":")) + "\n")
        sys.stdout.flush()
    return 1 if partial else 0


if __name__ == "__main__":
    raise SystemExit(main())
