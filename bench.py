#!/usr/bin/env python
"""Repo-root benchmark shim: steady + churn + contested suite, JSON out.

This is the harness entry point (``python bench.py``): it runs the
engine tick benchmark three times — an N=1k steady crash-burst, an N=1k
sustained-churn run, and an N=1k contested-consensus run through the
classic-Paxos fallback kernel — with defaults small enough to finish
quickly on CPU, and emits a single ``engine_tick_suite`` JSON payload.
When writing to stdout the payload is one compact line (the *last*
line, so harnesses that parse the stdout tail always get the whole
object); ``--out FILE`` writes the indented form. Each sub-payload
carries the per-run protocol summary in its ``telemetry`` block
(``rapid_tpu.telemetry.metrics.RunSummary``), validatable with::

    python -m rapid_tpu.telemetry.schema BENCH.json

For sweeps, tracing, and scenario knobs use the full benchmark:
``python benchmarks/bench_engine.py --help``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from benchmarks.bench_engine import run, run_churn, run_contested  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--n", type=int, default=1_000,
                        help="simulated cluster size (default 1k)")
    parser.add_argument("--ticks", type=int, default=120,
                        help="simulated ticks per run (default 120)")
    parser.add_argument("--burst", type=int, default=8,
                        help="churn run: slots per join/leave burst")
    parser.add_argument("--seed", type=int, default=0,
                        help="perturbs the synthetic node identities")
    parser.add_argument("--out", type=str, default=None,
                        help="write the JSON artifact to FILE "
                             "(default: stdout)")
    args = parser.parse_args(argv)

    from rapid_tpu.settings import Settings

    settings = Settings()
    payload = {
        "bench": "engine_tick_suite",
        "n": args.n,
        "ticks": args.ticks,
        "steady": run(args.n, args.ticks, crash_frac=0.01, crash_tick=5,
                      settings=settings, seed=args.seed),
        "churn": run_churn(args.n, args.ticks, args.burst, settings,
                           args.seed),
        "contested": run_contested(args.n, args.ticks, settings, args.seed),
    }
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(json.dumps(payload, indent=2) + "\n")
    else:
        sys.stdout.write(json.dumps(payload) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
