#!/usr/bin/env bash
# Tier-1 verification: the ROADMAP.md gate, wrapped so CI and humans run
# the exact same line. Prints DOTS_PASSED=<n> and exits with pytest's rc.
# If ruff is installed, a lint pass runs first (config in pyproject.toml);
# the container image does not ship it, so its absence is not a failure.
set -o pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    ruff check . || exit 1
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
