#!/usr/bin/env bash
# Tier-1 verification: the ROADMAP.md gate, wrapped so CI and humans run
# the exact same line. Prints DOTS_PASSED=<n> and exits with pytest's rc.
# If ruff is installed, a lint pass runs first (config in pyproject.toml);
# the container image does not ship it, so its absence is not a failure.
set -o pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    ruff check . || exit 1
    echo LINT=ok
else
    echo LINT=skipped
fi

rm -f /tmp/_t1.log
timeout -k 10 1200 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

# Telemetry smoke + regression gate: the root bench shim must emit a
# schema-valid payload whose deterministic protocol counts match the
# committed benchmarks/baseline.json exactly (bench_compare.py hard-fails
# on drift, warns on >30% ticks/s regression). Same config as the
# baseline: N=256, 120 ticks, so the steady crash burst actually decides
# (~tick 113) and the counts are non-trivial. Only meaningful when the
# test suite itself passed.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 600 env JAX_PLATFORMS=cpu python bench.py \
            --n 256 --ticks 120 --out /tmp/_t1_bench.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_bench.json \
        && python scripts/bench_compare.py /tmp/_t1_bench.json; then
        echo BENCH_SMOKE=ok
    else
        echo BENCH_SMOKE=failed
        rc=1
    fi
fi

# Contested-consensus smoke: the classic-Paxos fallback scenario must run
# end to end (48 ticks fits two contested instances) and emit a payload
# that carries the per-phase fallback telemetry.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python benchmarks/bench_engine.py \
            --scenario contested --n 256 --ticks 48 \
            --out /tmp/_t1_contested.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_contested.json; then
        echo CONTESTED_SMOKE=ok
    else
        echo CONTESTED_SMOKE=failed
        rc=1
    fi
fi

# Fault-adversary smoke: the one-way-partition scenario must run the
# host discrete-event engine against the oracle end to end — the run
# itself asserts bit-identity before emitting counts — and the payload
# must carry the partition gauges the schema requires.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python benchmarks/bench_engine.py \
            --scenario partition --n 48 --ticks 300 \
            --out /tmp/_t1_partition.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_partition.json; then
        echo PARTITION_SMOKE=ok
    else
        echo PARTITION_SMOKE=failed
        rc=1
    fi
fi

# Fleet-campaign smoke: a small Monte-Carlo campaign must sample the
# scenario space, run as one vmapped dispatch, emit a schema-valid
# campaign payload, and pass one oracle spot-check (the partition member
# is replayed through run_adversarial_differential, which raises on any
# per-slot divergence).
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 8 --fleet-size 8 --n 32 --ticks 160 \
            --spot-checks 1 --out /tmp/_t1_fleet.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_fleet.json; then
        echo FLEET_SMOKE=ok
    else
        echo FLEET_SMOKE=failed
        rc=1
    fi
fi

# Dispatch-observatory smoke: a small campaign run with --trace and
# --progress must emit (a) a schema-valid payload whose
# dispatch_timeline carries per-stage walls, (b) a parseable Perfetto
# trace-event JSON, and (c) at least one JSONL heartbeat line. The
# schema validator already enforces the stage-sum-vs-wall_s tolerance,
# so this step only checks the artifacts exist and parse. Fleet size 2
# forces a pool to span >=2 dispatches so the per-pool executable cache
# provably hits (a compiled dispatch followed by a cache-hit one).
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 8 --fleet-size 2 --n 32 --ticks 120 \
            --out /tmp/_t1_obs.json --trace /tmp/_t1_obs_trace.json \
            --progress /tmp/_t1_obs_progress.jsonl >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_obs.json \
        && python -c '
import json, sys
payload = json.load(open("/tmp/_t1_obs.json"))
timeline = payload["dispatch_timeline"]
trace = json.load(open("/tmp/_t1_obs_trace.json"))
heartbeats = [json.loads(line) for line in
              open("/tmp/_t1_obs_progress.jsonl") if line.strip()]
ok = (len(timeline) >= 2
      and timeline[0]["compiled"]
      and any(not r["compiled"] for r in timeline[1:])
      and payload["clusters_per_sec"] is not None
      and len(trace.get("traceEvents", [])) > 0
      and sum(1 for h in heartbeats if h.get("record") == "dispatch") >= 1)
sys.exit(0 if ok else 1)'; then
        echo OBSERVATORY_SMOKE=ok
    else
        echo OBSERVATORY_SMOKE=failed
        rc=1
    fi
fi

# Pipelined-dispatch smoke: the double-buffered campaign driver
# (--pipeline, the default) must produce a payload bit-identical to the
# serial driver (--no-pipeline) in every non-wall field — same pools,
# same timeline structure, same folded telemetry — while the
# observatory's pipeline block records which driver ran. The heartbeat
# stream must validate against the v7 progress schema (pool identity +
# live in-flight depth per dispatch).
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 8 --fleet-size 4 --n 32 --ticks 120 \
            --progress /tmp/_t1_pipe_progress.jsonl \
            --out /tmp/_t1_pipe.json >/dev/null \
        && timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 8 --fleet-size 4 --n 32 --ticks 120 \
            --no-pipeline --out /tmp/_t1_serial.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_pipe.json \
        && python -m rapid_tpu.telemetry.schema --progress \
            /tmp/_t1_pipe_progress.jsonl \
        && python -c '
import json, sys
WALL = ("boot_s", "wall_s", "fold_s", "compile_s", "device_busy_s",
        "host_blocked_s", "spot_check_s", "total_s", "ticks_per_sec",
        "rounds_per_sec", "clusters_per_sec", "observatory")
DISPATCH_WALL = ("stages", "wall_s", "clusters_per_sec",
                 "host_blocked_frac", "memory")
def strip(p):
    p = {k: v for k, v in p.items() if k not in WALL}
    p["dispatch_timeline"] = [
        {k: v for k, v in r.items() if k not in DISPATCH_WALL}
        for r in p["dispatch_timeline"]]
    return p
pipe = json.load(open("/tmp/_t1_pipe.json"))
serial = json.load(open("/tmp/_t1_serial.json"))
ok = (json.dumps(strip(pipe), sort_keys=True)
      == json.dumps(strip(serial), sort_keys=True)
      and pipe["observatory"]["pipeline"]["enabled"]
      and pipe["observatory"]["pipeline"]["max_in_flight"] == 2
      and not serial["observatory"]["pipeline"]["enabled"]
      and serial["observatory"]["pipeline"]["peak_in_flight"] == 1)
sys.exit(0 if ok else 1)'; then
        echo PIPELINE_SMOKE=ok
    else
        echo PIPELINE_SMOKE=failed
        rc=1
    fi
fi

# Partition-exact smoke: an N=64 campaign must dispatch its link-fault
# members through the per-receiver engine (device-exact protocol state
# per slot) and the partition spot-check must replay through
# run_receiver_differential — the payload has to show a passed spot
# member in per_receiver mode, not the shared-state referee.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 6 --fleet-size 6 --n 64 --ticks 160 \
            --spot-checks 1 --out /tmp/_t1_rx.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_rx.json \
        && python -c '
import json, sys
camp = json.load(open("/tmp/_t1_rx.json"))["campaign"]
pr = camp["per_receiver"]
spot = camp["spot_checks"]["members"]
ok = (pr["enabled"] and pr["members"] >= 1
      and pr["member_state_bytes"] > 0
      and any(m["kind"] == "partition" and m["mode"] == "per_receiver"
              and m["passed"] for m in spot))
sys.exit(0 if ok else 1)'; then
        echo PARTITION_EXACT_SMOKE=ok
    else
        echo PARTITION_EXACT_SMOKE=failed
        rc=1
    fi
fi

# Delay-adversary smoke: a latency-only campaign (every member draws
# fixed delay, bounded jitter, or slow-link asymmetry) must route all
# members through the per-receiver delivery ring, emit a schema-v6
# payload whose campaign.delay_regimes block carries non-empty
# ticks-to-first-decide tails for at least two latency regimes, and
# pass a delay-family spot check replayed bit-identically through
# run_receiver_differential (--spot-checks 3 covers the required
# partition/contested/delay kinds; the delay member comes from the
# campaign's own pool).
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python benchmarks/bench_engine.py \
            --scenario delay --clusters 6 --fleet-size 6 --n 48 --ticks 240 \
            --spot-checks 3 --out /tmp/_t1_delay.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_delay.json \
        && python -c '
import json, sys
camp = json.load(open("/tmp/_t1_delay.json"))["campaign"]
regimes = camp["delay_regimes"]
latency = [k for k in ("delay", "jitter", "slow_asym")
           if regimes.get(k, {}).get("count", 0) >= 1]
pr = camp["per_receiver"]
spot = camp["spot_checks"]["members"]
ok = (len(latency) >= 2
      and pr["enabled"] and pr["ring_depth"] >= 1
      and pr["members"] == camp["clusters"]
      and any(m["kind"] in ("delay", "jitter", "slow_asym")
              and m["mode"] == "per_receiver" and m["passed"]
              for m in spot))
sys.exit(0 if ok else 1)'; then
        echo DELAY_SMOKE=ok
    else
        echo DELAY_SMOKE=failed
        rc=1
    fi
fi

# Packed-receiver smoke: the same N=64 per-receiver campaign on the
# packed bit-plane layout (--rx-kernel packed). Spot checks replay
# through run_receiver_differential with the campaign's own settings,
# so the host referee bit-compares the packed device run; the payload
# must echo the layout and show the diet (packed member bytes strictly
# below the dense figure it also echoes).
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 6 --fleet-size 6 --n 64 --ticks 160 \
            --rx-kernel packed --spot-checks 1 \
            --out /tmp/_t1_rxpacked.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_rxpacked.json \
        && python -c '
import json, sys
camp = json.load(open("/tmp/_t1_rxpacked.json"))["campaign"]
pr = camp["per_receiver"]
spot = camp["spot_checks"]["members"]
ok = (pr["enabled"] and pr["rx_kernel"] == "packed"
      and pr["member_state_bytes"] < pr["member_state_bytes_unpacked"]
      and any(m["mode"] == "per_receiver" and m["passed"] for m in spot))
sys.exit(0 if ok else 1)'; then
        echo RX_PACKED_SMOKE=ok
    else
        echo RX_PACKED_SMOKE=failed
        rc=1
    fi
fi

# Pallas-kernel smoke: one delay+partition member under
# rx_kernel="pallas" (the packed carry plus the pallas deliver/
# aggregate kernel, interpreted on CPU) must be bit-identical to the
# dense XLA run — finals, logs and flags — at N=64. This is the
# device-exactness gate for the hand-written kernel.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -c '
import numpy as np
from rapid_tpu.engine import fleet as fleet_mod
from rapid_tpu.engine import receiver as rx_mod
from rapid_tpu.faults import AdversarySchedule, DelayRule, LinkWindow
from rapid_tpu.settings import Settings

n = 64
sched = AdversarySchedule(
    n=n,
    windows=(LinkWindow(src_slots=frozenset(range(8)),
                        dst_slots=frozenset(range(8, n)),
                        start_tick=20, end_tick=60, two_way=True),),
    delays=(DelayRule(src_slots=frozenset(range(0, 16)),
                      dst_slots=frozenset(range(16, 40)),
                      delay_ticks=1, jitter_ticks=2,
                      start_tick=5, end_tick=70),),
    seed=11)
xla = Settings()
member = fleet_mod.lower_receiver_schedule(sched, xla)
want_final, want_logs = rx_mod.receiver_simulate(
    member.state, member.faults, 80, xla)
got_final, got_logs = rx_mod.receiver_simulate(
    member.state, member.faults, 80, xla.with_(rx_kernel="pallas"))
for a, b in ((got_final, want_final), (got_logs, want_logs)):
    for field, x, y in zip(type(a)._fields, a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), field
rx_mod.check_flags(int(np.asarray(got_final.flags)))
print("pallas bit-identical over", len(want_logs._fields), "log fields")
'; then
        echo RX_PALLAS_SMOKE=ok
    else
        echo RX_PALLAS_SMOKE=failed
        rc=1
    fi
fi

# Triage + replay smoke: a recorder-on campaign must emit a schema-v8
# triage block that flags at least one member with a full exemplar
# (expected fold + flight-recorder ring), and `python -m
# rapid_tpu.replay` must reconstruct that member from the payload alone
# and prove bit-identity — the replay CLI itself exits 1 on any
# expected-block or recorder-ring mismatch, so its rc is the verdict.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 8 --fleet-size 4 --n 24 --ticks 120 \
            --flight-recorder 24 --out /tmp/_t1_triage.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_triage.json \
        && ref=$(python -c '
import json, sys
triage = json.load(open("/tmp/_t1_triage.json"))["campaign"]["triage"]
if triage["flagged_members"] < 1:
    sys.exit(1)
for block in triage["classes"].values():
    for ex in block["exemplars"]:
        if ex["expected"] is not None and ex["recorder"] is not None:
            print("%d:%d" % (ex["dispatch"], ex["member_index"]))
            sys.exit(0)
sys.exit(1)') \
        && timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.replay \
            --payload /tmp/_t1_triage.json --member "$ref" >/dev/null; then
        echo TRIAGE_SMOKE=ok
    else
        echo TRIAGE_SMOKE=failed
        rc=1
    fi
fi

# Lineage smoke: a recorder-on campaign must emit schema-v12 lineage
# tails (per-kind + aggregate phase-duration distributions folded from
# the same per-tick gauges the triage path already proves exact), a
# flagged exemplar must carry its member's lineage spans, and `replay
# --lineage --trace` must re-derive those spans from the payload alone
# (the CLI exits 1 on lineage mismatch) while the Perfetto export
# parses and contains proposal-stamped lineage slices.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 8 --fleet-size 4 --n 16 --ticks 120 --seed 3 \
            --flight-recorder 24 --out /tmp/_t1_lineage.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_lineage.json \
        && ref=$(python -c '
import json, sys
from rapid_tpu.telemetry.schema import validate_campaign_lineage
payload = json.load(open("/tmp/_t1_lineage.json"))
camp = payload["campaign"]
lin = camp["lineage"]
validate_campaign_lineage(lin)
if lin["spans"] < 1 or not lin["by_kind"]:
    sys.exit(1)
for block in camp["triage"]["classes"].values():
    for ex in block["exemplars"]:
        if ex["recorder"] is not None and ex.get("lineage"):
            print("%d:%d" % (ex["dispatch"], ex["member_index"]))
            sys.exit(0)
sys.exit(1)') \
        && timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.replay \
            --payload /tmp/_t1_lineage.json --member "$ref" --lineage \
            --trace /tmp/_t1_lineage_trace.json >/dev/null \
        && python -c '
import json, sys
trace = json.load(open("/tmp/_t1_lineage_trace.json"))
events = trace.get("traceEvents", [])
lineage = [e for e in events
           if e.get("args", {}).get("proposal") is not None]
sys.exit(0 if lineage else 1)'; then
        echo LINEAGE_SMOKE=ok
    else
        echo LINEAGE_SMOKE=failed
        rc=1
    fi
fi

# Streaming-soak smoke: the resident service must run >=2k ticks as
# donated chunked scans under open-loop traffic, perform one mid-soak
# checkpoint save/restore round trip (the CLI itself exits 1 unless the
# restored carry, continuation logs, final state and recorder ring are
# all bit-identical and the steady live-buffer watermark stayed flat),
# emit a schema-valid streaming JSONL stream, and print a parseable
# stream_summary line whose checkpoint block carries the proof.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.service \
            --soak --ticks 2048 --chunk 256 --n 16 --capacity 48 \
            --recorder 8 --no-tick-rows --out /tmp/_t1_soak.jsonl \
            > /tmp/_t1_soak.out \
        && python -m rapid_tpu.telemetry.schema --streaming \
            /tmp/_t1_soak.jsonl \
        && tail -n 1 /tmp/_t1_soak.out | python -c '
import json, sys
s = json.loads(sys.stdin.read())
ck = s["checkpoint"]
ok = (s["record"] == "stream_summary"
      and s["ticks"] >= 2048
      and ck["state_identical"] and ck["logs_identical"]
      and ck["final_identical"] and ck["recorder_identical"]
      and ck["continuation_recorder_identical"]
      and s["events_injected"] > 0 and s["decisions"] > 0)
sys.exit(0 if ok else 1)'; then
        echo SOAK_SMOKE=ok
    else
        echo SOAK_SMOKE=failed
        rc=1
    fi
fi

# Servo smoke: a short closed-loop soak under the target-rate load
# servo with the live status API attached. The JSONL stream must
# validate (schema v10: chunk-0 compile_s split, servo + rolling slo
# blocks on every heartbeat), the status file must hold a schema-valid
# status_snapshot, a concurrent `watch` subscriber must receive at
# least one schema-valid snapshot line over the unix socket while the
# run is live, and the summary's servo block must carry the target and
# a committed quantized rate.
if [ "$rc" -eq 0 ]; then
    rm -f /tmp/_t1_status.sock /tmp/_t1_watch.json
    timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.service \
            --soak --ticks 1024 --chunk 256 --n 16 --capacity 48 \
            --recorder 8 --no-tick-rows --target-rate 50 --slo-window 4 \
            --status /tmp/_t1_status.json \
            --status-socket /tmp/_t1_status.sock \
            --out /tmp/_t1_servo.jsonl > /tmp/_t1_servo.out &
    servo_pid=$!
    python -c '
import socket, sys, time
deadline = time.time() + 240
line = b""
while time.time() < deadline and not line:
    try:
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.settimeout(max(1.0, deadline - time.time()))
        c.connect("/tmp/_t1_status.sock")
        c.sendall(b"watch\n")
        line = c.makefile("rb").readline()
        c.close()
    except OSError:
        time.sleep(0.2)
sys.stdout.write(line.decode())' > /tmp/_t1_watch.json
    if wait "$servo_pid" \
        && test -s /tmp/_t1_watch.json \
        && python -m rapid_tpu.telemetry.schema --status \
            /tmp/_t1_watch.json \
        && python -m rapid_tpu.telemetry.schema --streaming \
            /tmp/_t1_servo.jsonl \
        && python -m rapid_tpu.telemetry.schema --status \
            /tmp/_t1_status.json \
        && tail -n 1 /tmp/_t1_servo.out | python -c '
import json, sys
s = json.loads(sys.stdin.read())
chunks = [json.loads(line) for line in open("/tmp/_t1_servo.jsonl")
          if json.loads(line).get("record") == "chunk"]
servo = s["servo"]
q = servo["config"]["rate_quantum_per_ktick"]
rate = servo["final"]["rate_per_ktick"]
ok = (s["record"] == "stream_summary"
      and servo["config"]["target_events_per_sec"] == 50.0
      and abs(rate / q - round(rate / q)) < 1e-9
      and s["compile_s"] is not None
      and chunks and chunks[0]["compile_s"] is not None
      and all(c["compile_s"] is None for c in chunks[1:])
      and all(c["servo"] is not None and c["slo"] is not None
              for c in chunks))
sys.exit(0 if ok else 1)'; then
        echo SERVO_SMOKE=ok
    else
        echo SERVO_SMOKE=failed
        rc=1
    fi
fi

# Receiver-resident smoke: the per-receiver twin of the soak (packed
# carry, two-zone schedule) must run chunked with one mid-run
# checkpoint save/restore round trip — the CLI exits 1 unless the
# restored packed carry, continuation logs, final state and recorder
# ring are all bit-identical — and its stream must validate.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.service \
            --rx-soak --n 64 --ticks 64 --chunk 16 --recorder 4 \
            --slo-window 4 --out /tmp/_t1_rxsoak.jsonl \
            > /tmp/_t1_rxsoak.out \
        && python -m rapid_tpu.telemetry.schema --streaming \
            /tmp/_t1_rxsoak.jsonl \
        && tail -n 1 /tmp/_t1_rxsoak.out | python -c '
import json, sys
s = json.loads(sys.stdin.read())
ck = s["checkpoint"]
ok = (s["record"] == "stream_summary"
      and s["source"] == "resident_receiver"
      and ck["state_identical"] and ck["logs_identical"]
      and ck["final_identical"] and ck["recorder_identical"]
      and ck["continuation_recorder_identical"])
sys.exit(0 if ok else 1)'; then
        echo RX_RESIDENT_SMOKE=ok
    else
        echo RX_RESIDENT_SMOKE=failed
        rc=1
    fi
fi

# Kernel-profile smoke: the per-kernel cost observatory must lower every
# sub-kernel and emit a schema-valid dominance report (small N, few
# repeats — the full 1k/10k/100k sweep is run manually; see
# benchmarks/dominance_report.json). bench_compare.py then diffs the
# N=256 per-kernel wall medians against the committed sweep — warn-only
# (wall time is machine-dependent); only a K/kernel-set mismatch fails.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python benchmarks/bench_engine.py \
            --profile-sweep --profile-sizes 256 --profile-repeats 2 \
            --out /tmp/_t1_profile.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_profile.json \
        && python scripts/bench_compare.py /tmp/_t1_profile.json; then
        echo PROFILE_SMOKE=ok
    else
        echo PROFILE_SMOKE=failed
        rc=1
    fi
fi

# Protocol-variant smoke: the ring and hierarchical variants must prove
# bit-identity against the variant-aware host oracle at N=64 (a
# three-crash burst each; assert_identical raises on any divergence in
# decisions, per-tick message counts or final config ids), and a small
# two-variant tournament must run every sampled member once per variant
# over identical schedules and emit a schema-valid payload whose
# campaign.tournament block carries both variants' decide tails and the
# per-kind win/loss ledger. Latency kinds are zeroed because variant
# members run the shared-state engine (per-receiver delivery is
# reference-protocol-only); the committed 256-member artifact is
# benchmarks/campaign_tournament.json.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -c '
from rapid_tpu.engine.diff import run_variant_differential
for variant in ("ring", "hier"):
    res = run_variant_differential(64, {3: 5, 17: 5, 40: 7}, 130, variant)
    res.assert_identical()
    print(variant, "bit-identical,", res.engine_message_total, "messages")
' \
        && timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rapid_tpu.campaign \
            --clusters 8 --fleet-size 8 --n 32 --ticks 160 \
            --weights delay=0,jitter=0,slow_asym=0 \
            --tournament rapid,ring \
            --out /tmp/_t1_tournament.json >/dev/null \
        && python -m rapid_tpu.telemetry.schema /tmp/_t1_tournament.json \
        && python -c '
import json, sys
camp = json.load(open("/tmp/_t1_tournament.json"))["campaign"]
tour = camp["tournament"]
ok = (camp["protocol_variant"] == "rapid"
      and sorted(tour["variants"]) == ["rapid", "ring"]
      and tour["clusters"] == 8
      and all(v in tour["per_variant"] for v in tour["variants"])
      and all(set(tour["variants"]) | {"tie"} <= set(row)
              for row in tour["win_loss"].values()))
sys.exit(0 if ok else 1)'; then
        echo VARIANT_SMOKE=ok
    else
        echo VARIANT_SMOKE=failed
        rc=1
    fi
fi

# Multi-chip smoke: the dry-run entrypoint must boot BASELINE config #1
# on the forced 8-device CPU mesh, run the sharded tick loop, and print
# a parseable result line with ok=true (three-way bit-identity: sharded
# == single-device == oracle). The entrypoint forces the host-platform
# override itself, so no XLA_FLAGS are needed here.
if [ "$rc" -eq 0 ]; then
    if timeout -k 10 300 env JAX_PLATFORMS=cpu python -m __graft_entry__ \
            > /tmp/_t1_multichip.out \
        && tail -n 1 /tmp/_t1_multichip.out | python -c '
import json, sys
line = json.loads(sys.stdin.read())
sys.exit(0 if line.get("ok") is True else 1)'; then
        echo MULTICHIP_SMOKE=ok
    else
        echo MULTICHIP_SMOKE=failed
        rc=1
    fi
fi
exit $rc
