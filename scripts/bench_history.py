#!/usr/bin/env python
"""Bench-history trend report: fold the harness's per-round capture
records into one table.

The capture harness drops ``BENCH_rNN.json`` / ``MULTICHIP_rNN.json``
records at the repo root after each round — ``{n, cmd, rc, tail,
parsed}`` where ``parsed`` is the JSON of the bench shim's last stdout
line (the compact suite payload ``bench.py`` always flushes), and
``{n_devices, rc, ok, skipped, tail}`` for the multi-chip dry run. This
tool reads every record plus ``benchmarks/baseline.json`` and prints a
per-round trend of the throughput figures that matter (per-suite
ticks/sec, the fleet campaign's clusters/sec) against the committed
baseline.

``SOAK_rNN.json`` records (same ``{n, rc, tail}`` shape, capturing a
``python -m rapid_tpu.service --soak`` run) are folded too: the
streaming columns come from the final ``stream_summary`` heartbeat on
the tail's last line, and a soak round whose tail does *not* end in
that record is flagged as having lost its final heartbeat — the soak
died between its last chunk and the summary flush.

``LOADSWEEP_rNN.json`` records (capturing ``python -m
rapid_tpu.service --load-sweep``) follow the same contract with a
``load_sweep`` payload on the tail's last line: a round whose tail
ends in anything else *lost its final block* (the sweep died between
its last rate and the payload flush). Healthy sweeps contribute the
knee columns — the largest stable target in events/sec and the
windowed p99 ticks-to-view-change measured at that knee.

``TOURNAMENT_rNN.json`` records (capturing ``python -m
rapid_tpu.campaign --tournament V1,V2``) again follow the tail
contract: the campaign CLI flushes the full payload as its last
stdout line, and a tournament round's payload must carry the
``campaign.tournament`` block. Healthy rounds contribute one line per
variant — decided members, p99 decide tick and total protocol
messages — plus the per-kind win/loss ledger, so a variant regressing
against the reference protocol shows up as a trend, not just a
one-off artifact diff.

Dead records are the whole point: a round whose ``tail`` is empty or
whose ``parsed`` is null means the bench ran but its output was lost —
historically a wall-budget kill with nothing flushed (``bench.py`` now
emits the summary line even on partial completion, so new dead records
indicate a capture bug, not a budget cut). Every such record is flagged
loudly on stderr and ``--strict`` turns any dead/partial round into
exit 1.

Usage::

    python scripts/bench_history.py            # repo-root records
    python scripts/bench_history.py --dir PATH --json out.json --strict
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Suite entries whose ticks_per_sec trend is worth a column (matches
#: bench.py's SUITE_ENTRIES; fleet reports clusters_per_sec instead —
#: streaming additionally reports events/sec and the p99
#: ticks-to-view-change tail in their own columns).
RATE_ENTRIES = ("steady", "churn", "contested", "partition", "delay",
                "streaming")


def _round_no(path: str, record: Dict) -> int:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    if m:
        return int(m.group(1))
    n = record.get("n")
    return n if isinstance(n, int) else -1


def _rate(entry: Optional[Dict], key: str) -> Optional[float]:
    if not isinstance(entry, dict):
        return None
    value = entry.get(key)
    return value if isinstance(value, (int, float)) else None


def _rx_rate(fleet: Optional[Dict]) -> Optional[float]:
    """Per-receiver engine throughput in member-ticks/sec, folded from
    the campaign's dispatch timeline: every member of a per_receiver
    dispatch advances ``ticks`` protocol ticks during that dispatch's
    ``execute`` stage, so the rate is sum(members * ticks) over
    sum(execute walls). None when the payload predates the timeline or
    ran no per-receiver dispatches."""
    if not isinstance(fleet, dict):
        return None
    ticks = fleet.get("ticks")
    timeline = fleet.get("dispatch_timeline")
    if not isinstance(ticks, (int, float)) or \
            not isinstance(timeline, list):
        return None
    member_ticks = 0.0
    execute_s = 0.0
    for rec in timeline:
        if not isinstance(rec, dict) or rec.get("mode") != "per_receiver":
            continue
        members = rec.get("members")
        stages = rec.get("stages")
        if not isinstance(members, (int, float)) or \
                not isinstance(stages, dict):
            continue
        wall = stages.get("execute")
        if not isinstance(wall, (int, float)):
            continue
        member_ticks += members * ticks
        execute_s += wall
    if member_ticks <= 0 or execute_s <= 0:
        return None
    return member_ticks / execute_s


def _streaming_cols(parsed: Optional[Dict]) -> Dict[str, Optional[float]]:
    """The streaming entry's load figures: sustained events/sec and the
    p99 ticks-to-view-change tail under that load. None for payloads
    predating the streaming entry (schema < 9)."""
    entry = parsed.get("streaming") if isinstance(parsed, dict) else None
    if not isinstance(entry, dict):
        return {"streaming_events_per_sec": None,
                "streaming_ttvc_p99": None,
                "streaming_lineage_diss_p99": None,
                "streaming_lineage_fallback_p99": None}
    ttvc = entry.get("ticks_to_view_change")
    lineage = _lineage_cols(entry.get("lineage"))
    return {"streaming_events_per_sec": _rate(entry, "events_per_sec"),
            "streaming_ttvc_p99": _rate(ttvc, "p99")
            if isinstance(ttvc, dict) else None,
            "streaming_lineage_diss_p99": lineage["lineage_diss_p99"],
            "streaming_lineage_fallback_p99":
                lineage["lineage_fallback_p99"]}


def _lineage_cols(block: Optional[Dict]) -> Dict[str, Optional[float]]:
    """p99 phase-duration tails from a lineage summary block (schema
    v12, ``LINEAGE_SUMMARY_SPEC``): where the view changes spent their
    ticks — dissemination vs fallback wait. None for payloads predating
    lineage."""
    durations = block.get("durations") if isinstance(block, dict) else None
    if not isinstance(durations, dict):
        return {"lineage_diss_p99": None, "lineage_fallback_p99": None}

    def p99(name):
        dist = durations.get(name)
        return _rate(dist, "p99") if isinstance(dist, dict) else None

    return {"lineage_diss_p99": p99("dissemination_ticks"),
            "lineage_fallback_p99": p99("fallback_wait")}


def _fold_bench(path: str) -> Dict[str, object]:
    """One BENCH_rNN.json -> a trend row (never raises: unreadable
    records become dead rows, which is exactly what we report)."""
    row: Dict[str, object] = {"path": os.path.basename(path),
                              "round": -1, "rc": None, "dead": True,
                              "partial": None, "rates": {},
                              "clusters_per_sec": None, "config": None,
                              "problems": []}
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, ValueError) as err:
        row["problems"].append(f"unreadable record: {err}")
        return row
    row["round"] = _round_no(path, record)
    row["rc"] = record.get("rc")
    tail = record.get("tail")
    parsed = record.get("parsed")
    if parsed is None and isinstance(tail, str) and tail.strip():
        # The harness may store the tail unparsed; recover it here.
        try:
            parsed = json.loads(tail.strip().splitlines()[-1])
        except ValueError:
            row["problems"].append("tail is not JSON")
    if not isinstance(tail, str) or not tail.strip():
        row["problems"].append("empty tail — bench output lost")
    if not isinstance(parsed, dict):
        row["problems"].append("no parsed payload")
        return row
    row["dead"] = False
    row["config"] = {"n": parsed.get("n"), "ticks": parsed.get("ticks")}
    partial = parsed.get("partial")
    if isinstance(partial, dict):
        row["partial"] = partial
        row["problems"].append(
            f"partial run: missing {partial.get('missing')} "
            f"({partial.get('error')})")
    row["rates"] = {name: _rate(parsed.get(name), "ticks_per_sec")
                    for name in RATE_ENTRIES}
    row["clusters_per_sec"] = _rate(parsed.get("fleet"),
                                    "clusters_per_sec")
    row["rx_member_ticks_per_sec"] = _rx_rate(parsed.get("fleet"))
    row.update(_streaming_cols(parsed))
    return row


def _fold_soak(path: str) -> Dict[str, object]:
    """One SOAK_rNN.json capture record -> a trend row.

    Soak captures mirror the bench ones (``{n, rc, tail}`` with the tail
    holding the soak's stdout) but their contract is different: the last
    stdout line must be the resident service's final ``stream_summary``
    heartbeat. A round whose tail ends in anything else *lost its final
    heartbeat* — the soak died (or was killed) between its last chunk
    and the summary flush — and is flagged exactly like a dead bench
    round.
    """
    row: Dict[str, object] = {"path": os.path.basename(path),
                              "round": -1, "rc": None, "dead": True,
                              "lost_final_heartbeat": True,
                              "ticks": None, "events_per_sec": None,
                              "ttvc_p99": None, "checkpoint_ok": None,
                              "lineage_diss_p99": None,
                              "lineage_fallback_p99": None,
                              "problems": []}
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, ValueError) as err:
        row["problems"].append(f"unreadable record: {err}")
        return row
    row["round"] = _round_no(path, record)
    row["rc"] = record.get("rc")
    tail = record.get("tail")
    if not isinstance(tail, str) or not tail.strip():
        row["problems"].append("empty tail — soak output lost")
        return row
    row["dead"] = False
    try:
        summary = json.loads(tail.strip().splitlines()[-1])
    except ValueError:
        summary = None
    if not isinstance(summary, dict) or \
            summary.get("record") != "stream_summary":
        row["problems"].append(
            "lost final heartbeat — tail does not end in a "
            "stream_summary record")
        return row
    row["lost_final_heartbeat"] = False
    ttvc = summary.get("ticks_to_view_change")
    ck = summary.get("checkpoint")
    row.update(
        ticks=summary.get("ticks"),
        events_per_sec=_rate(summary, "events_per_sec"),
        ttvc_p99=_rate(ttvc, "p99") if isinstance(ttvc, dict) else None,
        checkpoint_ok=all(ck.get(key) for key in
                          ("state_identical", "logs_identical",
                           "final_identical"))
        if isinstance(ck, dict) else None,
        **_lineage_cols(summary.get("lineage")))
    if row["checkpoint_ok"] is False:
        row["problems"].append("mid-soak checkpoint round trip was not "
                               "bit-identical")
    return row


def _fold_loadsweep(path: str) -> Dict[str, object]:
    """One LOADSWEEP_rNN.json capture record -> a trend row.

    Sweep captures mirror the soak ones (``{n, rc, tail}``) but the
    last stdout line must be the sweep's final ``load_sweep`` payload.
    A round whose tail ends in anything else *lost its final block* —
    the sweep died (or was killed) between its last rate and the
    payload flush — and is flagged exactly like a lost heartbeat. Note
    a sweep that ran but found no knee (all targets stable, or all
    unstable) exits nonzero yet still flushes the payload: that round
    folds cleanly with ``knee_events_per_sec`` null and its nonzero
    ``rc`` visible.
    """
    row: Dict[str, object] = {"path": os.path.basename(path),
                              "round": -1, "rc": None, "dead": True,
                              "lost_final_block": True,
                              "targets": None, "n_stable": None,
                              "n_unstable": None,
                              "knee_events_per_sec": None,
                              "knee_achieved_events_per_sec": None,
                              "knee_ttvc_p99": None,
                              "problems": []}
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, ValueError) as err:
        row["problems"].append(f"unreadable record: {err}")
        return row
    row["round"] = _round_no(path, record)
    row["rc"] = record.get("rc")
    tail = record.get("tail")
    if not isinstance(tail, str) or not tail.strip():
        row["problems"].append("empty tail — sweep output lost")
        return row
    row["dead"] = False
    try:
        payload = json.loads(tail.strip().splitlines()[-1])
    except ValueError:
        payload = None
    if not isinstance(payload, dict) or \
            payload.get("record") != "load_sweep":
        row["problems"].append(
            "lost final block — tail does not end in a load_sweep "
            "record")
        return row
    row["lost_final_block"] = False
    rates = payload.get("rates")
    rates = rates if isinstance(rates, list) else []
    stable = [r for r in rates
              if isinstance(r, dict) and r.get("stable") is True]
    row.update(targets=payload.get("targets"),
               n_stable=len(stable),
               n_unstable=sum(1 for r in rates
                              if isinstance(r, dict)
                              and r.get("stable") is False))
    knee = payload.get("knee")
    if isinstance(knee, dict):
        row.update(
            knee_events_per_sec=_rate(knee, "target_events_per_sec"),
            knee_achieved_events_per_sec=_rate(
                knee, "achieved_events_per_sec"),
            knee_ttvc_p99=_rate(knee, "ticks_to_view_change_p99"))
    else:
        row["problems"].append(
            "no knee — every target classified the same way "
            "(widen --targets)")
    return row


def _fold_tournament(path: str) -> Dict[str, object]:
    """One TOURNAMENT_rNN.json capture record -> a trend row.

    Tournament captures mirror the soak ones (``{n, rc, tail}``) but
    the last stdout line must be a campaign payload whose ``campaign``
    block carries ``tournament``. A round whose tail ends in anything
    else *lost its final payload* and is flagged like a lost heartbeat.
    Healthy rounds fold one entry per variant (decided count, p99
    decide tick, total messages) plus the per-kind win/loss ledger.
    """
    row: Dict[str, object] = {"path": os.path.basename(path),
                              "round": -1, "rc": None, "dead": True,
                              "lost_final_payload": True,
                              "clusters": None, "variants": {},
                              "win_loss": None, "problems": []}
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, ValueError) as err:
        row["problems"].append(f"unreadable record: {err}")
        return row
    row["round"] = _round_no(path, record)
    row["rc"] = record.get("rc")
    tail = record.get("tail")
    if not isinstance(tail, str) or not tail.strip():
        row["problems"].append("empty tail — tournament output lost")
        return row
    row["dead"] = False
    try:
        payload = json.loads(tail.strip().splitlines()[-1])
    except ValueError:
        payload = None
    camp = payload.get("campaign") if isinstance(payload, dict) else None
    tour = camp.get("tournament") if isinstance(camp, dict) else None
    if not isinstance(tour, dict):
        row["problems"].append(
            "lost final payload — tail does not end in a campaign "
            "payload with a tournament block")
        return row
    row["lost_final_payload"] = False
    row["clusters"] = tour.get("clusters")
    row["win_loss"] = tour.get("win_loss")
    per_variant = tour.get("per_variant")
    if isinstance(per_variant, dict):
        for name, block in sorted(per_variant.items()):
            if not isinstance(block, dict):
                continue
            ticks = block.get("decide_ticks")
            row["variants"][name] = {
                "decided": block.get("decided"),
                "total_messages": block.get("total_messages"),
                "decide_p99": _rate(ticks, "p99")
                if isinstance(ticks, dict) else None,
                **_lineage_cols(block.get("lineage"))}
    if not row["variants"]:
        row["problems"].append("tournament block has no per-variant "
                               "entries")
    return row


def _fold_multichip(path: str) -> Dict[str, object]:
    row: Dict[str, object] = {"path": os.path.basename(path),
                              "round": -1, "rc": None, "ok": None,
                              "skipped": None, "problems": []}
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, ValueError) as err:
        row["problems"].append(f"unreadable record: {err}")
        return row
    row["round"] = _round_no(path, record)
    row.update(rc=record.get("rc"), ok=record.get("ok"),
               skipped=record.get("skipped"))
    if record.get("ok") is not True and not record.get("skipped"):
        row["problems"].append("multichip round neither ok nor skipped")
    return row


def _baseline_row(path: str) -> Optional[Dict[str, object]]:
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        baseline = json.load(fh)
    row = {"path": os.path.relpath(path, _REPO), "round": None,
           "rc": 0, "dead": False, "partial": None,
           "config": {"n": baseline.get("n"),
                      "ticks": baseline.get("ticks")},
           "rates": {name: _rate(baseline.get(name), "ticks_per_sec")
                     for name in RATE_ENTRIES},
           "clusters_per_sec": _rate(baseline.get("fleet"),
                                     "clusters_per_sec"),
           "rx_member_ticks_per_sec": _rx_rate(baseline.get("fleet")),
           "problems": []}
    row.update(_streaming_cols(baseline))
    return row


def _fmt(value: Optional[float]) -> str:
    if value is None:
        return "--"
    return f"{value:.0f}" if value >= 10 else f"{value:.2f}"


def build_report(directory: str, baseline_path: str) -> Dict[str, object]:
    bench_rows = [_fold_bench(p) for p in
                  sorted(glob.glob(os.path.join(directory,
                                                "BENCH_r*.json")))]
    multichip_rows = [_fold_multichip(p) for p in
                      sorted(glob.glob(os.path.join(
                          directory, "MULTICHIP_r*.json")))]
    soak_rows = [_fold_soak(p) for p in
                 sorted(glob.glob(os.path.join(directory,
                                               "SOAK_r*.json")))]
    sweep_rows = [_fold_loadsweep(p) for p in
                  sorted(glob.glob(os.path.join(directory,
                                                "LOADSWEEP_r*.json")))]
    tournament_rows = [_fold_tournament(p) for p in
                       sorted(glob.glob(os.path.join(
                           directory, "TOURNAMENT_r*.json")))]
    return {"record": "bench_history",
            "directory": directory,
            "baseline": _baseline_row(baseline_path),
            "rounds": bench_rows,
            "multichip": multichip_rows,
            "soak": soak_rows,
            "load_sweep": sweep_rows,
            "tournament": tournament_rows,
            "dead_rounds": [r["path"] for r in bench_rows if r["dead"]]
                           + [r["path"] for r in soak_rows
                              if r["dead"] or r["lost_final_heartbeat"]]
                           + [r["path"] for r in sweep_rows
                              if r["dead"] or r["lost_final_block"]]
                           + [r["path"] for r in tournament_rows
                              if r["dead"] or r["lost_final_payload"]],
            "partial_rounds": [r["path"] for r in bench_rows
                               if r["partial"]]}


def render(report: Dict[str, object]) -> str:
    lines = []
    header = (["round", "rc"] + list(RATE_ENTRIES)
              + ["str ev/s", "str p99", "str diss99", "str fb99",
                 "fleet cl/s", "rx mt/s", "flags"])
    rows: List[List[str]] = []
    baseline = report["baseline"]
    for row in ([baseline] if baseline else []) + list(report["rounds"]):
        label = "baseline" if row["round"] is None else f"r{row['round']:02d}"
        flags = ("DEAD" if row["dead"]
                 else "PARTIAL" if row["partial"] else "ok")
        rows.append([label, str(row["rc"])]
                    + [_fmt(row["rates"].get(name))
                       for name in RATE_ENTRIES]
                    + [_fmt(row.get("streaming_events_per_sec")),
                       _fmt(row.get("streaming_ttvc_p99")),
                       _fmt(row.get("streaming_lineage_diss_p99")),
                       _fmt(row.get("streaming_lineage_fallback_p99")),
                       _fmt(row["clusters_per_sec"]),
                       _fmt(row.get("rx_member_ticks_per_sec")), flags])
    if report.get("no_live_rounds"):
        # An empty trajectory reads as "no data yet", not a silently
        # empty table: one explicit banner row below the baseline.
        rows.append(["no-live-rounds", "--"]
                    + ["--"] * (len(header) - 3) + ["NO DATA"])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              if rows else len(header[i]) for i in range(len(header))]
    lines.append("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    if report.get("no_live_rounds"):
        lines.append("no-live-rounds: the harness has captured no "
                     "BENCH_r*/MULTICHIP_r*/SOAK_r*/LOADSWEEP_r*/"
                     "TOURNAMENT_r* records yet (baseline only)")
    for row in report["multichip"]:
        state = ("ok" if row["ok"] else
                 "skipped" if row["skipped"] else "FAILED")
        lines.append(f"multichip r{row['round']:02d}: {state} "
                     f"(rc={row['rc']})")
    for row in report.get("soak", []):
        if row["dead"]:
            state = "DEAD"
        elif row["lost_final_heartbeat"]:
            state = "LOST FINAL HEARTBEAT"
        elif row["checkpoint_ok"] is False:
            state = "CHECKPOINT MISMATCH"
        else:
            state = (f"ok ({row['ticks']} ticks, "
                     f"{_fmt(row['events_per_sec'])} ev/s, "
                     f"ttvc p99 {_fmt(row['ttvc_p99'])})")
            if row.get("lineage_diss_p99") is not None \
                    or row.get("lineage_fallback_p99") is not None:
                state += (f" [diss p99 {_fmt(row['lineage_diss_p99'])}, "
                          f"fb p99 {_fmt(row['lineage_fallback_p99'])}]")
        lines.append(f"soak r{row['round']:02d}: {state} "
                     f"(rc={row['rc']})")
    for row in report.get("load_sweep", []):
        if row["dead"]:
            state = "DEAD"
        elif row["lost_final_block"]:
            state = "LOST FINAL BLOCK"
        elif row["knee_events_per_sec"] is None:
            state = (f"NO KNEE ({row['n_stable']} stable / "
                     f"{row['n_unstable']} unstable)")
        else:
            state = (f"knee {_fmt(row['knee_events_per_sec'])} ev/s "
                     f"(achieved "
                     f"{_fmt(row['knee_achieved_events_per_sec'])}, "
                     f"ttvc p99 {_fmt(row['knee_ttvc_p99'])}; "
                     f"{row['n_stable']} stable / "
                     f"{row['n_unstable']} unstable)")
        lines.append(f"load-sweep r{row['round']:02d}: {state} "
                     f"(rc={row['rc']})")
    for row in report.get("tournament", []):
        if row["dead"]:
            state = "DEAD"
        elif row["lost_final_payload"]:
            state = "LOST FINAL PAYLOAD"
        else:
            cols = []
            for name, block in sorted(row["variants"].items()):
                entry = (f"{name}: {block['decided']}/{row['clusters']} "
                         f"decided, p99 {_fmt(block['decide_p99'])}, "
                         f"{block['total_messages']} msgs")
                if block.get("lineage_diss_p99") is not None \
                        or block.get("lineage_fallback_p99") is not None:
                    entry += (f" [diss p99 "
                              f"{_fmt(block.get('lineage_diss_p99'))}, "
                              f"fb p99 "
                              f"{_fmt(block.get('lineage_fallback_p99'))}]")
                cols.append(entry)
            wins = row.get("win_loss") or {}
            won = {name: sum(kinds.get(name, 0)
                             for kinds in wins.values()
                             if isinstance(kinds, dict))
                   for name in list(row["variants"]) + ["tie"]}
            cols.append("wins " + "/".join(
                f"{name}={won[name]}" for name in sorted(won)))
            state = "; ".join(cols)
        lines.append(f"tournament r{row['round']:02d}: {state} "
                     f"(rc={row['rc']})")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--dir", default=_REPO,
                        help="directory holding BENCH_r*/MULTICHIP_r* "
                             "records (default: repo root)")
    parser.add_argument("--baseline",
                        default=os.path.join(_REPO, "benchmarks",
                                             "baseline.json"),
                        help="committed baseline for the reference row")
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="also write the folded report as JSON")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 when any round is dead or partial")
    args = parser.parse_args(argv)

    report = build_report(args.dir, args.baseline)
    if not report["rounds"] and not report["multichip"] \
            and not report["soak"] and not report["load_sweep"] \
            and not report["tournament"]:
        # "No data yet" is a healthy state, not a failure: render the
        # baseline with an explicit no-live-rounds banner row and exit 0
        # even under --strict (there is nothing dead to gate on).
        report["no_live_rounds"] = True
        print(render(report))
        print(f"bench_history: no live rounds under {args.dir} "
              f"(--strict exempt: an empty trajectory is 'no data "
              f"yet', not a dead round)", file=sys.stderr)
        if args.json:
            with open(args.json, "w") as fh:
                json.dump(report, fh, indent=2)
                fh.write("\n")
        return 0
    print(render(report))
    for row in (report["rounds"] + report["multichip"]
                + report["soak"] + report["load_sweep"]
                + report["tournament"]):
        for problem in row["problems"]:
            print(f"bench_history: WARNING: {row['path']}: {problem}",
                  file=sys.stderr)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")
    bad = report["dead_rounds"] + report["partial_rounds"]
    if args.strict and bad:
        print(f"bench_history: {len(bad)} dead/partial round(s): "
              f"{', '.join(bad)}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
