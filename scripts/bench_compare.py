#!/usr/bin/env python
"""Bench-regression gate: diff a bench payload against the committed
baseline.

Protocol counts in the engine are fully deterministic — the synthetic
identities, the crash burst, the churn plan, and the contested-consensus
schedule are all seeded — so announcements, decisions, per-view-change
message traffic, per-phase fallback counts, and invariant-violation
counts must match the committed ``benchmarks/baseline.json`` *exactly*;
any drift is a protocol change that either updates the baseline
deliberately or is a bug. The fleet entry's ``dispatch_timeline``
(schema v5) splits the same way: structural fields (dispatch count,
routing mode, kind mix, padding waste, compile-on-first-dispatch) are
seed-deterministic and diff exactly, while stage walls stay out of the
diff. Wall-clock throughput is machine-dependent, so ``ticks_per_sec``
and ``clusters_per_sec`` regressions only warn (default tolerance 30%).

``kernel_profile_sweep`` payloads (``--profile-sweep``) are also
accepted: runs are matched by ``n`` against the committed
``benchmarks/dominance_report.json`` (picked automatically when
``--baseline`` is left at its default) and per-kernel wall-clock medians
diff warn-only — wall time is machine-dependent, so only a K mismatch or
a kernel disappearing from the sweep is an error. The sweep's
``variants`` block (schema v11, ``--variant-sizes``) splits the same
way: the config row and the dense-broadcast refusal arithmetic diff
exactly, variant kernel walls warn.

Campaign payloads carry the whole ``campaign`` block — including a
``tournament`` block when present — through the exact union-of-keys
diff, so the committed ``benchmarks/campaign_tournament.json`` gates a
same-config rerun bit-for-bit (``--baseline`` selects it).

Usage (wired into ``scripts/tier1.sh``)::

    python bench.py --n 256 --ticks 120 --out /tmp/bench.json
    python scripts/bench_compare.py /tmp/bench.json

Exit codes: 0 = clean (warnings allowed), 1 = protocol drift, schema
violation, or config mismatch, 2 = usage. ``--update-baseline`` rewrites
the baseline from the current payload (after schema validation) for
deliberate protocol changes.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from rapid_tpu.telemetry.schema import (validate_bench_payload,  # noqa: E402
                                        validate_load_sweep)

#: Seed-deterministic config of a ``record: "load_sweep"`` artifact —
#: exact-gated, like every other config block. The measured figures
#: (achieved rates, backlog slopes, stability verdicts, the knee) are
#: wall-clock-dependent and warn-only: the committed sweep documents
#: *this machine's* knee, not a protocol invariant.
LOAD_SWEEP_CONFIG_KEYS = (
    "record", "schema_version", "n", "capacity", "chunk_ticks",
    "chunks_per_rate", "warmup_chunks", "seed",
    "backlog_slope_threshold", "targets",
)

#: Run-config keys that must match for the count comparison to mean
#: anything; a mismatch is an error telling the caller to regenerate.
CONFIG_KEYS = ("n", "ticks", "k", "clusters", "fleet_size", "capacity",
               "chunk_ticks")

#: Deterministic protocol counts at the run level (compared when present
#: on either side — scenarios carry different subsets). The streaming
#: entry's traffic (seeded arrival process), chunk structure,
#: decide-latency tail, and checkpoint bit-exactness verdicts are all
#: deterministic, so they gate exactly like any other protocol count;
#: its ``events_per_sec`` rate is wall-clock and stays warn-only.
PROTOCOL_RUN_KEYS = (
    "announcements", "decisions", "final_members", "crashed_nodes",
    "churn_bursts", "burst_size", "contested_instances",
    "ticks_to_first_decide", "messages_per_view_change",
    "events_injected", "joins", "leaves", "bursts", "chunks",
    "traffic", "ticks_to_view_change", "lineage", "checkpoint",
)

#: Seed-deterministic structural fields of one dispatch_timeline record
#: (schema v7 adds the pool identity and its stacking maxima); stage
#: walls, rates, and memory watermarks are machine-dependent and only
#: warn.
DISPATCH_STRUCTURAL_KEYS = (
    "index", "mode", "pool_id", "pool_shape", "members", "pad_members",
    "fleet_size", "kinds", "compiled", "padding",
)

#: Deterministic protocol counts inside the telemetry block, including
#: the full per-view-change rows and the per-phase fallback traffic.
PROTOCOL_TELEMETRY_KEYS = (
    "announcements", "decisions", "ticks_to_first_announce",
    "ticks_to_first_decide", "messages_per_view_change", "total_sent",
    "total_delivered", "total_dropped", "total_timeouts",
    "total_probes_sent", "total_probes_failed", "invariant_violations",
    "fallback_phase_sent", "view_changes", "max_partitioned_edges",
    "total_link_dropped",
)


def compare_run(current: Dict, baseline: Dict, where: str,
                tps_tolerance: float,
                cps_tolerance: float = None
                ) -> Tuple[List[str], List[str]]:
    """Diff one run payload; returns (errors, warnings)."""
    errors: List[str] = []
    warnings: List[str] = []

    for key in CONFIG_KEYS:
        if current.get(key) != baseline.get(key):
            errors.append(
                f"{where}.{key}: config mismatch (current "
                f"{current.get(key)!r} vs baseline {baseline.get(key)!r}) "
                f"— regenerate the baseline with --update-baseline")
            return errors, warnings  # counts are meaningless across configs

    for key in PROTOCOL_RUN_KEYS:
        if key not in current and key not in baseline:
            continue
        if current.get(key) != baseline.get(key):
            errors.append(f"{where}.{key}: {current.get(key)!r} != "
                          f"baseline {baseline.get(key)!r}")

    cur_tel = current.get("telemetry") or {}
    base_tel = baseline.get("telemetry") or {}
    for key in PROTOCOL_TELEMETRY_KEYS:
        if cur_tel.get(key) != base_tel.get(key):
            errors.append(f"{where}.telemetry.{key}: {cur_tel.get(key)!r} "
                          f"!= baseline {base_tel.get(key)!r}")

    # Fleet campaigns: every field of the campaign block (scenario-kind
    # counts, spot-check results, nearest-rank distributions, and the
    # schema-v8 triage block — per-class anomaly counts, exemplar refs,
    # extracted recorder rings) is derived from the campaign seed, so it
    # must match exactly like any other protocol count. The triage block
    # is required to stay wall-clock-free for exactly this reason.
    if "campaign" in current or "campaign" in baseline:
        cur_c = current.get("campaign") or {}
        base_c = baseline.get("campaign") or {}
        for key in sorted(set(cur_c) | set(base_c)):
            if cur_c.get(key) != base_c.get(key):
                errors.append(f"{where}.campaign.{key}: "
                              f"{cur_c.get(key)!r} != baseline "
                              f"{base_c.get(key)!r}")

    # Dispatch observatory (schema v5): the timeline's structure —
    # dispatch count, member routing, kind mixes, padding waste, the
    # compile-on-dispatch-0 flag — is seed-deterministic and compares
    # exactly; stage walls, throughput rates, and memory watermarks are
    # machine-dependent and stay out of the exact diff.
    if "dispatch_timeline" in current or "dispatch_timeline" in baseline:
        cur_t = current.get("dispatch_timeline") or []
        base_t = baseline.get("dispatch_timeline") or []
        if len(cur_t) != len(base_t):
            errors.append(
                f"{where}.dispatch_timeline: {len(cur_t)} dispatch "
                f"record(s) != baseline {len(base_t)}")
        for i, (cur_d, base_d) in enumerate(zip(cur_t, base_t)):
            for key in DISPATCH_STRUCTURAL_KEYS:
                if cur_d.get(key) != base_d.get(key):
                    errors.append(
                        f"{where}.dispatch_timeline[{i}].{key}: "
                        f"{cur_d.get(key)!r} != baseline "
                        f"{base_d.get(key)!r}")

    # Throughput regressions are warn-only (wall clock is
    # machine-dependent); clusters_per_sec — the fleet pipeline's
    # headline rate — gets its own tolerance knob so campaign throughput
    # can be watched tighter or looser than raw tick throughput.
    rate_tolerances = (
        ("ticks_per_sec", tps_tolerance),
        ("events_per_sec", tps_tolerance),
        ("clusters_per_sec",
         tps_tolerance if cps_tolerance is None else cps_tolerance),
    )
    for rate_key, tolerance in rate_tolerances:
        cur_rate = current.get(rate_key)
        base_rate = baseline.get(rate_key)
        if isinstance(cur_rate, (int, float)) and \
                isinstance(base_rate, (int, float)) and base_rate > 0:
            if cur_rate < base_rate * (1.0 - tolerance):
                drop = 100.0 * (1.0 - cur_rate / base_rate)
                warnings.append(
                    f"{where}.{rate_key}: {cur_rate} is {drop:.0f}% below "
                    f"baseline {base_rate} (tolerance "
                    f"{tolerance * 100:.0f}%)")
    return errors, warnings


def compare_profile_sweeps(current: Dict, baseline: Dict,
                           wall_tolerance: float
                           ) -> Tuple[List[str], List[str]]:
    """Diff two ``kernel_profile_sweep`` payloads.

    Runs match by ``n`` (the smoke sweeps a subset of the committed
    sizes, so extra baseline sizes are fine; a current size absent from
    the baseline is skipped with a warning). Per-kernel wall medians are
    machine-dependent and only warn past ``wall_tolerance``; a K
    mismatch or a kernel row missing from the current sweep is an error.
    """
    errors: List[str] = []
    warnings: List[str] = []
    base_runs = {run.get("n"): run for run in baseline.get("runs", [])}
    for run in current.get("runs", []):
        n = run.get("n")
        where = f"payload.runs[n={n}]"
        base = base_runs.get(n)
        if base is None:
            warnings.append(f"{where}: no baseline run at this n "
                            f"(baseline sizes {sorted(base_runs)})")
            continue
        if run.get("k") != base.get("k"):
            errors.append(f"{where}.k: config mismatch (current "
                          f"{run.get('k')!r} vs baseline {base.get('k')!r})"
                          f" — regenerate with --update-baseline")
            continue
        base_kernels = {k["kernel"]: k for k in base.get("kernels", [])}
        cur_kernels = {k["kernel"]: k for k in run.get("kernels", [])}
        for name in sorted(set(base_kernels) - set(cur_kernels)):
            errors.append(f"{where}: kernel {name!r} in baseline but "
                          f"missing from current sweep")
        for name, cur_k in sorted(cur_kernels.items()):
            base_k = base_kernels.get(name)
            if base_k is None:
                warnings.append(f"{where}: new kernel {name!r} not in "
                                f"baseline")
                continue
            cur_w = cur_k.get("wall_median_s")
            base_w = base_k.get("wall_median_s")
            if isinstance(cur_w, (int, float)) and \
                    isinstance(base_w, (int, float)) and base_w > 0 and \
                    cur_w > base_w * (1.0 + wall_tolerance):
                up = 100.0 * (cur_w / base_w - 1.0)
                warnings.append(
                    f"{where}.{name}.wall_median_s: {cur_w:.3e} is "
                    f"{up:.0f}% above baseline {base_w:.3e} (tolerance "
                    f"{wall_tolerance * 100:.0f}%)")

    # Multichip block: wall numbers are machine-dependent (warn-only via
    # the schema's structural check); only the mesh *shape* is config. A
    # null/absent block on either side is fine — the tier-1 profile
    # smoke runs without forced devices and records null, while the
    # committed sweep carries real numbers.
    cur_mc = current.get("multichip")
    base_mc = baseline.get("multichip")
    if isinstance(cur_mc, dict) and isinstance(base_mc, dict):
        for key in ("n_devices", "axis"):
            if cur_mc.get(key) != base_mc.get(key):
                errors.append(
                    f"payload.multichip.{key}: config mismatch (current "
                    f"{cur_mc.get(key)!r} vs baseline {base_mc.get(key)!r})"
                    f" — regenerate with --update-baseline")

    # Receiver-memory block: same null-tolerance as multichip (a smoke
    # profile may skip it with --no-receiver-memory while the committed
    # sweep carries it). The config row and the per-member byte figure
    # are pure shape arithmetic, so they diff exactly; XLA's temp/peak
    # estimates and compile times are toolchain-dependent and warn-only.
    cur_rm = current.get("receiver_memory")
    base_rm = baseline.get("receiver_memory")
    if isinstance(cur_rm, dict) and isinstance(base_rm, dict):
        for key in ("n", "capacity", "k", "member_state_bytes"):
            if cur_rm.get(key) != base_rm.get(key):
                errors.append(
                    f"payload.receiver_memory.{key}: config mismatch "
                    f"(current {cur_rm.get(key)!r} vs baseline "
                    f"{base_rm.get(key)!r}) — regenerate with "
                    f"--update-baseline")
        base_fleets = {f.get("fleet_size"): f
                       for f in base_rm.get("fleets", [])}
        # The packed layout's analytic curve is shape arithmetic too —
        # dense/packed bytes per member must diff exactly (this is the
        # memory-diet claim the README table cites); absent on either
        # side means a pre-diet payload, which is fine.
        cur_curve = {row.get("capacity"): row
                     for row in cur_rm.get("bytes_per_member_curve", [])}
        base_curve = {row.get("capacity"): row
                      for row in base_rm.get("bytes_per_member_curve", [])}
        for cap in sorted(set(cur_curve) & set(base_curve)):
            for key in ("dense_bytes", "packed_carry_bytes",
                        "packed_bundle_bytes"):
                if cur_curve[cap].get(key) != base_curve[cap].get(key):
                    errors.append(
                        f"payload.receiver_memory.bytes_per_member_curve"
                        f"[C={cap}].{key}: {cur_curve[cap].get(key)!r} != "
                        f"baseline {base_curve[cap].get(key)!r}")
        for fl in cur_rm.get("fleets", []):
            fsz = fl.get("fleet_size")
            where = f"payload.receiver_memory.fleets[F={fsz}]"
            base_f = base_fleets.get(fsz)
            if base_f is None:
                warnings.append(f"{where}: no baseline fleet at this size "
                                f"(baseline sizes {sorted(base_fleets)})")
                continue
            for key in ("argument_bytes", "output_bytes"):
                if fl.get(key) != base_f.get(key):
                    errors.append(f"{where}.{key}: {fl.get(key)!r} != "
                                  f"baseline {base_f.get(key)!r}")
            for key in ("temp_bytes", "peak_bytes"):
                cur_v, base_v = fl.get(key), base_f.get(key)
                if isinstance(cur_v, int) and isinstance(base_v, int) and \
                        base_v > 0 and \
                        cur_v > base_v * (1.0 + wall_tolerance):
                    up = 100.0 * (cur_v / base_v - 1.0)
                    warnings.append(
                        f"{where}.{key}: {cur_v} is {up:.0f}% above "
                        f"baseline {base_v} (tolerance "
                        f"{wall_tolerance * 100:.0f}%)")

    # Protocol-variant block (schema v11): same null-tolerance as
    # multichip (the tier-1 smoke skips it, the committed sweep carries
    # the 1M ring entry). The config row and the refusals — which sizes
    # the dense broadcast was *refused* at, and the bytes arithmetic
    # behind each refusal — are deterministic and diff exactly: a
    # refusal silently disappearing means someone started materializing
    # the O(N^2) matrix again. Kernel wall medians warn like every other
    # profiled kernel.
    cur_vb = current.get("variants")
    base_vb = baseline.get("variants")
    if isinstance(cur_vb, dict) and isinstance(base_vb, dict):
        for key in ("sizes", "budget_bytes"):
            if cur_vb.get(key) != base_vb.get(key):
                errors.append(
                    f"payload.variants.{key}: config mismatch (current "
                    f"{cur_vb.get(key)!r} vs baseline {base_vb.get(key)!r})"
                    f" — regenerate with --update-baseline")
        cur_ref = {(r.get("kernel"), r.get("n")): r
                   for r in cur_vb.get("refusals", [])}
        base_ref = {(r.get("kernel"), r.get("n")): r
                    for r in base_vb.get("refusals", [])}
        for key in sorted(set(base_ref) - set(cur_ref)):
            errors.append(f"payload.variants.refusals: {key[0]!r} at "
                          f"n={key[1]} refused in baseline but attempted "
                          f"in current sweep")
        for key in sorted(set(cur_ref) & set(base_ref)):
            for field in ("bytes_required", "budget_bytes"):
                if cur_ref[key].get(field) != base_ref[key].get(field):
                    errors.append(
                        f"payload.variants.refusals[{key[0]}, n={key[1]}]"
                        f".{field}: {cur_ref[key].get(field)!r} != "
                        f"baseline {base_ref[key].get(field)!r}")
        base_vk = {(k.get("kernel"), k.get("n")): k
                   for k in base_vb.get("kernels", [])}
        for k in cur_vb.get("kernels", []):
            ref = (k.get("kernel"), k.get("n"))
            base_k = base_vk.get(ref)
            where = f"payload.variants.kernels[{ref[0]}, n={ref[1]}]"
            if base_k is None:
                warnings.append(f"{where}: not in baseline")
                continue
            cur_w = k.get("wall_median_s")
            base_w = base_k.get("wall_median_s")
            if isinstance(cur_w, (int, float)) and \
                    isinstance(base_w, (int, float)) and base_w > 0 and \
                    cur_w > base_w * (1.0 + wall_tolerance):
                up = 100.0 * (cur_w / base_w - 1.0)
                warnings.append(
                    f"{where}.wall_median_s: {cur_w:.3e} is {up:.0f}% "
                    f"above baseline {base_w:.3e} (tolerance "
                    f"{wall_tolerance * 100:.0f}%)")
    return errors, warnings


def compare_load_sweep(current: Dict, baseline: Dict,
                       tps_tolerance: float
                       ) -> Tuple[List[str], List[str]]:
    """Diff two ``record: "load_sweep"`` artifacts: sweep config and
    each rate's servo constants are exact; achieved throughput, the
    stability verdicts, and the knee itself are machine-dependent and
    only warn."""
    errors: List[str] = []
    warnings: List[str] = []
    for key in LOAD_SWEEP_CONFIG_KEYS:
        if current.get(key) != baseline.get(key):
            errors.append(
                f"payload.{key}: config mismatch (current "
                f"{current.get(key)!r} vs baseline {baseline.get(key)!r}) "
                f"— regenerate the baseline with --update-baseline")
    if errors:
        return errors, warnings  # rate rows are meaningless across configs

    cur_rates = current.get("rates") or []
    base_rates = baseline.get("rates") or []
    if len(cur_rates) != len(base_rates):
        errors.append(f"payload.rates: {len(cur_rates)} entries != "
                      f"baseline {len(base_rates)}")
    for i, (cur_r, base_r) in enumerate(zip(cur_rates, base_rates)):
        where = f"payload.rates[{i}]"
        for key in ("target_events_per_sec", "servo_config", "chunks"):
            if cur_r.get(key) != base_r.get(key):
                errors.append(f"{where}.{key}: {cur_r.get(key)!r} != "
                              f"baseline {base_r.get(key)!r}")
        if cur_r.get("stable") != base_r.get("stable"):
            warnings.append(
                f"{where}.stable: verdict flipped ({base_r.get('stable')} "
                f"-> {cur_r.get('stable')}) — the knee moved on this "
                f"machine")
        cur_a, base_a = (cur_r.get("achieved_events_per_sec"),
                         base_r.get("achieved_events_per_sec"))
        if isinstance(cur_a, (int, float)) and \
                isinstance(base_a, (int, float)) and base_a > 0 and \
                cur_a < base_a * (1.0 - tps_tolerance):
            drop = 100.0 * (1.0 - cur_a / base_a)
            warnings.append(
                f"{where}.achieved_events_per_sec: {cur_a} is "
                f"{drop:.0f}% below baseline {base_a} (tolerance "
                f"{tps_tolerance * 100:.0f}%)")
    cur_knee = (current.get("knee") or {}).get("target_events_per_sec")
    base_knee = (baseline.get("knee") or {}).get("target_events_per_sec")
    if cur_knee != base_knee:
        warnings.append(f"payload.knee.target_events_per_sec: {cur_knee!r}"
                        f" != baseline {base_knee!r} (machine-dependent)")
    return errors, warnings


def compare_payloads(current: Dict, baseline: Dict,
                     tps_tolerance: float,
                     wall_tolerance: float = 0.50,
                     cps_tolerance: float = None
                     ) -> Tuple[List[str], List[str]]:
    """Diff two schema-valid payloads (suite, single run, or sweep)."""
    cur_kind = current.get("bench")
    base_kind = baseline.get("bench")
    if cur_kind != base_kind:
        return ([f"payload.bench: kind mismatch (current {cur_kind!r} vs "
                 f"baseline {base_kind!r})"], [])
    if cur_kind == "kernel_profile_sweep":
        return compare_profile_sweeps(current, baseline, wall_tolerance)
    if cur_kind == "engine_tick_suite":
        errors: List[str] = []
        warnings: List[str] = []
        for key in ("steady", "churn", "contested", "partition", "delay",
                    "streaming", "fleet"):
            e, w = compare_run(current.get(key) or {},
                               baseline.get(key) or {},
                               f"payload.{key}", tps_tolerance,
                               cps_tolerance)
            errors += e
            warnings += w
        return errors, warnings
    return compare_run(current, baseline, "payload", tps_tolerance,
                       cps_tolerance)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("current", help="bench payload JSON to check")
    parser.add_argument("--baseline", default=None,
                        help="committed baseline payload (default "
                             "benchmarks/baseline.json, or benchmarks/"
                             "dominance_report.json for kernel_profile_"
                             "sweep payloads)")
    parser.add_argument("--tps-tolerance", type=float, default=0.30,
                        help="warn when ticks_per_sec drops more than "
                             "this fraction below baseline (default 0.30)")
    parser.add_argument("--cps-tolerance", type=float, default=0.30,
                        help="warn when a fleet campaign's "
                             "clusters_per_sec drops more than this "
                             "fraction below baseline (default 0.30; "
                             "warn-only — wall clock is machine-"
                             "dependent)")
    parser.add_argument("--wall-tolerance", type=float, default=0.50,
                        help="warn when a profiled kernel's wall median "
                             "rises more than this fraction above the "
                             "baseline sweep (default 0.50)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="overwrite the baseline with the current "
                             "payload (schema-validated) and exit 0")
    args = parser.parse_args(argv)

    with open(args.current) as fh:
        current = json.load(fh)
    is_sweep = current.get("record") == "load_sweep"
    if args.baseline is None:
        if is_sweep:
            name = "load_sweep.json"
        elif current.get("bench") == "kernel_profile_sweep":
            name = "dominance_report.json"
        else:
            name = "baseline.json"
        args.baseline = os.path.join(_REPO, "benchmarks", name)
    validate = validate_load_sweep if is_sweep else validate_bench_payload
    schema_errors = validate(current)
    if schema_errors:
        for e in schema_errors:
            print(f"bench_compare: current payload schema violation: {e}",
                  file=sys.stderr)
        return 1

    if args.update_baseline:
        from rapid_tpu.telemetry import write_json_artifact

        write_json_artifact(args.baseline, current, indent=2)
        print(f"bench_compare: baseline updated: {args.baseline}")
        return 0

    if not os.path.exists(args.baseline):
        print(f"bench_compare: no baseline at {args.baseline}; create one "
              f"with --update-baseline", file=sys.stderr)
        return 1
    with open(args.baseline) as fh:
        baseline = json.load(fh)
    schema_errors = validate(baseline)
    if schema_errors:
        for e in schema_errors:
            print(f"bench_compare: baseline schema violation: {e}",
                  file=sys.stderr)
        return 1

    if is_sweep:
        errors, warnings = compare_load_sweep(current, baseline,
                                              args.tps_tolerance)
    else:
        errors, warnings = compare_payloads(current, baseline,
                                            args.tps_tolerance,
                                            args.wall_tolerance,
                                            args.cps_tolerance)
    for w in warnings:
        print(f"bench_compare: WARNING: {w}", file=sys.stderr)
    if errors:
        for e in errors:
            print(f"bench_compare: protocol drift: {e}", file=sys.stderr)
        print(f"bench_compare: FAILED ({len(errors)} drift(s) vs "
              f"{args.baseline})", file=sys.stderr)
        return 1
    print(f"bench_compare: ok ({args.current} matches {args.baseline}"
          f"{', ' + str(len(warnings)) + ' warning(s)' if warnings else ''})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
